"""Numerics observatory (ISSUE 3): fused tensor stats vs numpy, the
eager FLAGS_check_nan_inf guard (immediate + deferred with replay
localization), jit stat taps through the compiled engines, the
cross-rank divergence sentinel (incl. a true 2-rank forced desync),
artifact schema round-trips, and the clip/AMP satellites."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import numerics as num
from paddle_tpu.core.tensor import Tensor

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _numerics_flags_reset():
    yield
    paddle.set_flags({'FLAGS_check_nan_inf': False,
                      'FLAGS_check_nan_inf_deferred': False,
                      'FLAGS_tensor_stats': False})
    num.reset()


def _count_fetches(monkeypatch):
    """Route the observatory's single host-sync hook through a counter."""
    calls = []
    real = num._host_fetch
    monkeypatch.setattr(num, '_host_fetch',
                        lambda tree: calls.append(1) or real(tree))
    return calls


# ---------------------------------------------------------------------------
# fused tensor stats
# ---------------------------------------------------------------------------
class TestTensorStats:
    def test_matches_numpy(self):
        a = np.array([1.0, -2.0, 0.0, np.nan, np.inf, -np.inf, 3.5, 0.0],
                     np.float32)
        st = num.tensor_stats(a)
        assert st.nan_count == 1
        assert st.inf_count == 2
        assert st.zero_count == 2
        assert st.nonfinite_count == 3
        fin = a[np.isfinite(a)]
        assert np.isclose(st.min, fin.min())
        assert np.isclose(st.max, fin.max())
        assert np.isclose(st.mean, fin.mean(), rtol=1e-6)
        assert np.isclose(st.rms, np.sqrt((fin ** 2).mean()), rtol=1e-6)
        assert np.isclose(st.l2_norm, np.sqrt((fin ** 2).sum()), rtol=1e-6)
        assert st.numel == 8
        assert st.shape == (8,) and st.dtype == 'float32'

    def test_subnormal_and_zero_disjoint(self):
        # FTZ backends may compare a subnormal equal to 0 — the two
        # buckets must stay disjoint regardless
        a = np.array([0.0, 1e-40, 1.0], np.float32)
        st = num.tensor_stats(a)
        assert st.subnormal_count == 1
        assert st.zero_count == 1

    def test_bfloat16_and_int(self):
        import jax.numpy as jnp
        st = num.tensor_stats(jnp.asarray([1.0, jnp.nan], jnp.bfloat16))
        assert st.nan_count == 1 and st.numel == 2
        sti = num.tensor_stats(np.array([0, 3, 0], np.int32))
        assert sti.zero_count == 2 and sti.nonfinite_count == 0
        assert np.isclose(sti.l2_norm, 3.0)

    def test_empty(self):
        st = num.tensor_stats(np.zeros((0, 4), np.float32))
        assert st.numel == 0 and st.nonfinite_count == 0

    def test_collect_batches_one_sync(self, monkeypatch):
        calls = _count_fetches(monkeypatch)
        named = {f't{i}': np.full((4,), i, np.float32) for i in range(12)}
        out = num.collect(named)
        assert len(calls) == 1                   # 12 tensors, one sync
        assert out['t3'].mean == 3.0
        assert out['t0'].zero_count == 4

    def test_as_dict_json_ready(self):
        d = num.tensor_stats(np.ones((2, 2), np.float32)).as_dict()
        json.dumps(d)
        assert d['shape'] == [2, 2] and d['numel'] == 4


# ---------------------------------------------------------------------------
# eager guard
# ---------------------------------------------------------------------------
class TestEagerGuardImmediate:
    def test_trips_at_the_op_with_structured_report(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv('FLEET_LOG_DIR', str(tmp_path))
        paddle.set_flags({'FLAGS_check_nan_inf': True})
        with pytest.raises(FloatingPointError) as ei:
            paddle.log(paddle.to_tensor([-1.0]))
        err = ei.value
        assert isinstance(err, num.NumericsError)
        rep = err.report
        assert rep['kind'] == 'numerics_report'
        assert rep['op'] == 'log'
        assert rep['mode'] == 'eager-immediate'
        assert rep['output']['stats']['nan_count'] == 1
        assert rep['inputs'][0]['stats']['nan_count'] == 0
        assert err.report_path and os.path.exists(err.report_path)
        with open(err.report_path) as f:
            assert json.load(f)['op'] == 'log'

    def test_clean_ops_do_not_trip(self):
        paddle.set_flags({'FLAGS_check_nan_inf': True})
        out = paddle.log(paddle.to_tensor([1.0, 2.0]))
        assert np.isfinite(out.numpy()).all()


class TestEagerGuardDeferred:
    def _flags(self):
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True})

    def test_localizes_origin_not_consumer(self):
        self._flags()
        x = paddle.to_tensor([0.25, 0.5])
        y = paddle.log(x - 1.0)            # origin: log of negatives
        z = y * 2.0                        # consumer inherits the NaN
        w = z + 1.0                        # noqa: F841 — more consumers
        with pytest.raises(num.NumericsError) as ei:
            num.flush(site='test', step=3)
        rep = ei.value.report
        assert rep['op'] == 'log'
        assert rep['mode'] == 'eager-deferred'
        assert rep['step'] == 3
        # the replay proves the op CREATED the NaN: inputs were finite
        assert all(i['stats']['nan_count'] == 0 and
                   i['stats']['inf_count'] == 0 for i in rep['inputs'])

    def test_clean_step_costs_exactly_one_sync(self, monkeypatch):
        self._flags()
        x = paddle.to_tensor([1.0, 2.0])
        for _ in range(5):
            x = paddle.log(x * x + 1.0)
        calls = _count_fetches(monkeypatch)
        assert num.flush() is None
        assert len(calls) == 1
        assert num.guard().pending_ops() == 0

    def test_flush_without_ops_is_free(self, monkeypatch):
        self._flags()
        calls = _count_fetches(monkeypatch)
        assert num.flush() is None
        assert not calls

    def test_journal_cap_bounds_memory(self):
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True,
                          'FLAGS_check_nan_inf_max_journal': 8})
        y = paddle.log(paddle.to_tensor([-1.0]))       # origin
        for _ in range(12):
            y = y * 1.0
        assert num.guard().pending_ops() == 8
        with pytest.raises(num.NumericsError) as ei:
            num.flush()
        assert ei.value.report['journal_dropped'] > 0
        paddle.set_flags({'FLAGS_check_nan_inf_max_journal': 4096})

    def test_optimizer_step_is_the_boundary_and_guards_params(self):
        """The deferred sync runs at optimizer.step BEFORE the update:
        a poisoned backward raises and leaves params untouched."""
        self._flags()
        paddle.seed(0)
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=net.parameters())
        w_before = np.asarray(net.weight.data).copy()
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        loss = paddle.log(net(x).sum() - 1e9)        # log(negative) -> nan
        loss.backward()
        with pytest.raises(num.NumericsError):
            opt.step()
        np.testing.assert_array_equal(np.asarray(net.weight.data),
                                      w_before)


# ---------------------------------------------------------------------------
# jit taps through the compiled engines
# ---------------------------------------------------------------------------
def _hybrid_engine(hidden=16):
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
        HybridParallelTrainStep)
    topology_runtime.build_mesh(['dp'], [1])
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, hidden), nn.ReLU(),
                        nn.Linear(hidden, 1))
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())

    def loss_fn(m, x, y):
        d = m(x) - y
        return (d * d).mean()

    eng = HybridParallelTrainStep(net, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = Tensor(rng.rand(4, 8).astype('float32'))
    y = Tensor(rng.rand(4, 1).astype('float32'))
    return eng, x, y


class TestJitTaps:
    def test_hybrid_engine_publishes_stats_one_sync_per_step(
            self, monkeypatch):
        paddle.set_flags({'FLAGS_tensor_stats': True})
        eng, x, y = _hybrid_engine()
        try:
            float(eng(x, y))                       # compile + warm
            calls = _count_fetches(monkeypatch)
            for _ in range(3):
                eng(x, y)
            assert len(calls) == 3                 # ONE sync per step
            taps = eng.last_numerics
            assert taps['grad_norm'] > 0
            assert set(taps['grads']) == set(eng._params)
            assert all(s.nonfinite_count == 0
                       for s in taps['grads'].values())
            from paddle_tpu.core import monitor
            g = monitor.metrics().get('ptpu_num_grad_norm_global')
            assert g is not None and g.value() > 0
        finally:
            eng.shutdown()

    def test_hybrid_engine_planted_nan_raises_naming_layer(self):
        import jax.numpy as jnp
        paddle.set_flags({'FLAGS_check_nan_inf': True})
        eng, x, y = _hybrid_engine()
        float(eng(x, y))
        name = next(n for n in eng._params if n.endswith('weight'))
        eng._params[name] = eng._params[name] * jnp.nan
        with pytest.raises(num.NumericsError) as ei:
            eng(x, y)
        rep = ei.value.report
        assert rep['mode'] == 'jit' and rep['site'] == 'hybrid'
        assert rep['first_bad']
        assert any(t['name'] == name for t in rep['tensors'])
        assert ei.value.report_path and \
            os.path.exists(ei.value.report_path)
        eng._closed = True          # poisoned params; skip shutdown

    def test_trainstep_taps_and_trip(self):
        import jax.numpy as jnp
        from paddle_tpu.jit import TrainStep
        paddle.set_flags({'FLAGS_check_nan_inf': True})
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 1))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        step = TrainStep(net, lambda m, a, b: ((m(a) - b) ** 2).mean(),
                         opt)
        rng = np.random.RandomState(0)
        x = Tensor(rng.rand(4, 8).astype('float32'))
        y = Tensor(rng.rand(4, 1).astype('float32'))
        float(step(x, y))
        assert step.last_numerics['grad_norm'] > 0
        k = next(iter(step._params))
        step._params[k] = step._params[k] * jnp.nan
        with pytest.raises(num.NumericsError) as ei:
            step(x, y)
        assert ei.value.report['site'] == 'jit'

    def test_pipeline_engine_taps(self):
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline \
            import SpmdPipelineEngine
        paddle.set_flags({'FLAGS_tensor_stats': True})
        topology_runtime.build_mesh(['dp', 'pp'], [1, 1])
        paddle.seed(0)
        H, V = 16, 11

        class Embed(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, H)

            def forward(self, ids):
                return self.emb(ids)

        class Head(nn.Layer):
            def __init__(self):
                super().__init__()
                self.proj = nn.Linear(H, V)

            def forward(self, h, labels):
                logits = self.proj(h)
                return nn.functional.cross_entropy(
                    logits.reshape([-1, V]),
                    labels.reshape([-1])).mean()

        eng = SpmdPipelineEngine(
            Embed(), [nn.Linear(H, H) for _ in range(2)], Head(),
            paddle.optimizer.SGD(learning_rate=0.1, parameters=[]),
            accumulate_steps=2)
        try:
            rng = np.random.RandomState(0)
            ids = Tensor(rng.randint(0, V, (4, 6)).astype('int32'))
            labels = Tensor(rng.randint(0, V, (4, 6)).astype('int64'))
            float(eng.train_batch((ids, labels)).data)
            taps = eng.last_numerics
            assert taps['grad_norm'] > 0
            assert any(k.startswith('blocks/') for k in taps['grads'])
            assert any(k.startswith('embed/') for k in taps['grads'])
            # the fp16-scaling mode keeps working with taps threaded
            float(eng.train_batch((ids, labels), scale=8.0).data)
            assert not bool(np.asarray(eng.last_found_inf))
            assert eng.last_numerics['grad_norm'] > 0
            # a loss-scale OVERFLOW step the engine survives (update
            # skipped via found_inf) must NOT trip the taps, even with
            # the guard armed — the GradScaler owns that recovery
            import jax.numpy as jnp
            paddle.set_flags({'FLAGS_check_nan_inf': True})
            name = next(iter(eng._params['embed']))
            eng._params['embed'][name] = \
                eng._params['embed'][name] * jnp.nan
            eng.train_batch((ids, labels), scale=8.0)   # no raise
            assert bool(np.asarray(eng.last_found_inf))
            assert eng.last_numerics is None
            eng._closed = True          # poisoned params; skip shutdown
        finally:
            if not eng._closed:
                eng.shutdown()


class TestJitTapsShardEscape:
    def test_nonfinite_global_norm_trips_without_local_offender(self):
        """Per-tensor taps are shard-local under mp/pp; the mesh-reduced
        global norm is the check a sharded NaN cannot evade."""
        import jax.numpy as jnp
        paddle.set_flags({'FLAGS_check_nan_inf': True})
        taps = {'grads': {'w': num.stats_vec(jnp.ones((4,)))},
                'params': {},
                'grad_norm_sq': jnp.asarray(jnp.nan, jnp.float32)}
        with pytest.raises(num.NumericsError) as ei:
            num.process_jit_taps(taps, site='hybrid', step=5)
        rep = ei.value.report
        assert rep['first_bad'] == '<global grad norm>'
        assert 'model-parallel shard or pipeline stage' in rep['message']


class TestGuardLifecycle:
    def test_amp_skip_step_resets_guard(self):
        """A GradScaler overflow skip is a SURVIVED nonfinite step: the
        deferred guard's flag/journal must not leak into (and crash) the
        next clean step."""
        from paddle_tpu.amp import GradScaler
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True})
        paddle.seed(0)
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 2), np.float32))
        loss = paddle.exp(net(x).sum() * 1e9)       # overflow -> inf
        loss.backward()
        scaler = GradScaler(init_loss_scaling=2.0,
                            decr_every_n_nan_or_inf=1)
        scaler.step(opt)                            # skipped, no raise
        assert scaler._found_inf
        assert num.guard().pending_ops() == 0       # state dropped
        opt.clear_grad()
        loss = (net(x) ** 2).mean()                 # clean step
        loss.backward()
        scaler.step(opt)                            # must NOT raise
        assert not scaler._found_inf

    def test_scaler_not_wedged_by_numerics_raise(self):
        """A NumericsError escaping optimizer.step() inside
        GradScaler.step must not leave _unscaled latched — a later step
        would silently apply still-scaled gradients."""
        from paddle_tpu.amp import GradScaler
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True})
        paddle.seed(0)
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        # journal a nonfinite op OUTSIDE the grads (grads stay finite,
        # so unscale_ passes and the boundary flush raises)
        paddle.log(paddle.to_tensor([-1.0]))
        for p in net.parameters():
            p.grad = Tensor(np.ones(p.shape, np.float32))
        scaler = GradScaler(init_loss_scaling=4.0)
        with pytest.raises(num.NumericsError):
            scaler.step(opt)
        assert not scaler._unscaled          # re-armed, not wedged
        # recovery: a fresh clean step unscales normally
        for p in net.parameters():
            p.grad = Tensor(np.full(p.shape, 4.0, np.float32))
        scaler.step(opt)
        assert not scaler._found_inf

    def test_journal_cap_zero_disables_replay_not_detection(self):
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True,
                          'FLAGS_check_nan_inf_max_journal': 0})
        try:
            paddle.log(paddle.to_tensor([-1.0]))
            assert num.guard().pending_ops() == 0    # nothing pinned
            with pytest.raises(num.NumericsError):   # flag still trips
                num.flush()
        finally:
            paddle.set_flags({'FLAGS_check_nan_inf_max_journal': 4096})

    def test_journal_cap_zero_still_checked_at_optimizer_boundary(self):
        """With an empty journal (cap 0) the accumulated device flag
        must still be flushed at optimizer.step — detection cannot be
        silently disabled by the memory bound."""
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True,
                          'FLAGS_check_nan_inf_max_journal': 0})
        try:
            paddle.seed(0)
            net = nn.Linear(2, 2)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            x = paddle.to_tensor(np.ones((2, 2), np.float32))
            loss = paddle.log(net(x).sum() - 1e9)
            loss.backward()
            assert num.guard().pending_ops() == 0
            assert num.guard().has_pending()
            with pytest.raises(num.NumericsError) as ei:
                opt.step()
            assert ei.value.report['op'] is None    # no journal: origin
            assert 'journal window' in ei.value.report['message']
        finally:
            paddle.set_flags({'FLAGS_check_nan_inf_max_journal': 4096})

    def test_clip_inside_optimizer_step_adds_no_second_sync(self):
        """With FLAGS_tensor_stats the optimizer boundary publishes the
        pre-clip norm from its one batched sync; ClipGradByGlobalNorm
        must not publish (and sync) again inside optimizer.step."""
        from paddle_tpu.core import monitor
        paddle.set_flags({'FLAGS_tensor_stats': True})
        paddle.seed(0)
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net.parameters(),
            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        for p in net.parameters():
            p.grad = Tensor(np.ones(p.shape, np.float32))
        before = monitor.metrics().get('ptpu_num_grad_norm_preclip')
        before_val = before.value(site='global_norm_clip') \
            if before is not None else None
        opt.step()
        g = monitor.metrics().get('ptpu_num_grad_norm_global')
        assert g is not None and g.value() > 0     # boundary published
        after = monitor.metrics().get('ptpu_num_grad_norm_preclip')
        after_val = after.value(site='global_norm_clip') \
            if after is not None else None
        assert after_val == before_val             # clip stayed silent

    def test_step_guard_exception_resets_instead_of_leaking(self):
        paddle.set_flags({'FLAGS_check_nan_inf': True,
                          'FLAGS_check_nan_inf_deferred': True})
        with pytest.raises(ValueError):
            with num.step_guard(step=1):
                paddle.log(paddle.to_tensor([-1.0]))   # journals a NaN
                raise ValueError('body failed')
        assert num.guard().pending_ops() == 0
        # the next clean step is not blamed for the failed one
        with num.step_guard(step=2):
            paddle.log(paddle.to_tensor([2.0]))


# ---------------------------------------------------------------------------
# divergence sentinel
# ---------------------------------------------------------------------------
class TestDivergenceSentinel:
    def test_vote_majority_and_tiebreak(self):
        s = num.DivergenceSentinel(group=object())
        fps = [np.array([1., 2., 3.]), np.array([1., 2., 3.]),
               np.array([1., 9., 3.])]
        consensus, offending = s._vote(fps)
        assert consensus == [0, 1] and offending == [2]
        # 2-rank tie breaks toward rank 0's value
        consensus, offending = s._vote([np.array([1., 2., 3.]),
                                        np.array([1., 2.5, 3.])])
        assert consensus == [0] and offending == [1]
        consensus, offending = s._vote([np.array([1., 2., 3.]),
                                        np.array([1., 2., 3.])])
        assert offending == []

    def test_shared_nan_is_agreement_not_divergence(self):
        """All ranks hitting the SAME nonfinite step is a numerics
        problem, not divergence — NaN fingerprints must vote together."""
        s = num.DivergenceSentinel(group=object())
        fp = np.array([np.nan, 2.0, 3.0])
        consensus, offending = s._vote([fp.copy() for _ in range(4)])
        assert offending == [] and consensus == [0, 1, 2, 3]

    def test_noop_without_group(self):
        s = num.DivergenceSentinel()
        assert s.check(0, grad_norm=1.0,
                       params={'w': np.ones(3, np.float32)}) is None

    def test_fingerprint_deterministic(self):
        s = num.DivergenceSentinel(group=object())
        p = {'w': np.arange(6, dtype=np.float32).reshape(2, 3),
             'b': Tensor(np.ones(2, np.float32))}
        f1 = s.fingerprint(grad_norm=0.5, params=p)
        f2 = s.fingerprint(grad_norm=0.5, params=p)
        np.testing.assert_array_equal(f1, f2)
        assert f1[0] == 0.5 and f1[1] == 17.0       # sum 0..5 + two 1s

    def test_two_rank_forced_desync(self, tmp_path):
        """ISSUE 3 acceptance: a forced 2-rank parameter desync produces
        a divergence report naming the first divergent step and the
        offending rank, on BOTH ranks, via the host-collective
        allgather."""
        s = socket.socket()
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1] - 7     # host backend adds +7
        s.close()
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.update({
                'PADDLE_TRAINER_ID': str(rank),
                'PADDLE_TRAINERS_NUM': '2',
                'PADDLE_MASTER': f'127.0.0.1:{port}',
                'JAX_PLATFORMS': 'cpu',
                'DIVERGENCE_DUMP_DIR': str(tmp_path),
            })
            env.pop('XLA_FLAGS', None)
            procs.append(subprocess.Popen(
                [sys.executable, '-u',
                 os.path.join(HERE, 'dist_models', 'dist_divergence.py')],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
        assert all(p.returncode == 0 for p in procs), outs
        reports = [f for f in os.listdir(tmp_path)
                   if f.startswith('divergence_report.rank')]
        assert len(reports) == 2, (os.listdir(tmp_path), outs)
        with open(os.path.join(tmp_path, sorted(reports)[0])) as f:
            rep = json.load(f)
        assert rep['kind'] == 'divergence_report'
        assert rep['first_divergent_step'] == 2
        assert rep['offending_ranks'] == [1]
        assert rep['world_size'] == 2
        text = num.render_divergence_report(rep)
        assert 'first divergent step: 2' in text
        assert '<-- divergent' in text


# ---------------------------------------------------------------------------
# artifact schema round trips through the CLI renderer
# ---------------------------------------------------------------------------
class TestArtifacts:
    def test_numerics_report_classify_and_render(self, tmp_path):
        sys.path.insert(0, os.path.join(os.path.dirname(HERE), 'tools'))
        import health_dump
        paddle.set_flags({'FLAGS_check_nan_inf': True})
        with pytest.raises(num.NumericsError) as ei:
            paddle.sqrt(paddle.to_tensor([-4.0]))
        rep = json.loads(json.dumps(ei.value.report))   # JSON round trip
        assert health_dump.classify(rep) == 'numerics_report'
        text = health_dump.render(rep)
        assert 'first nonfinite op: sqrt' in text
        assert 'nan=1' in text

    def test_divergence_report_via_cli_renderer(self):
        sys.path.insert(0, os.path.join(os.path.dirname(HERE), 'tools'))
        import health_dump
        rep = {'kind': 'divergence_report', 'step': 9,
               'first_divergent_step': 7, 'rank': 0, 'world_size': 4,
               'fingerprint_labels': list(num.FINGERPRINT_LABELS),
               'ranks': {str(r): [1.0, 2.0 + (r == 3), 3.0]
                         for r in range(4)},
               'offending_ranks': [3], 'consensus_ranks': [0, 1, 2]}
        rep = json.loads(json.dumps(rep))
        assert health_dump.classify(rep) == 'divergence_report'
        assert 'rank 3' in health_dump.render(rep)

    def test_step_telemetry_carries_numerics(self):
        from paddle_tpu.profiler import StepTelemetry
        snap = StepTelemetry(publish=False).snapshot()
        assert 'numerics' in snap
        assert 'grad_norm_global' in snap['numerics']
        json.dumps(snap['numerics'])


# ---------------------------------------------------------------------------
# satellites: clip + AMP
# ---------------------------------------------------------------------------
class TestClipGradNorm:
    def _param_with_grad(self, g):
        p = Tensor(np.ones_like(g), stop_gradient=False)
        p.grad = Tensor(np.asarray(g))
        return p

    def test_error_if_nonfinite_raises(self):
        p = self._param_with_grad(np.array([np.inf, 1.0], np.float32))
        with pytest.raises(RuntimeError, match='non-finite'):
            nn.clip_grad_norm_([p], max_norm=1.0, error_if_nonfinite=True)

    def test_nonfinite_tolerated_when_not_asked(self):
        p = self._param_with_grad(np.array([np.inf, 1.0], np.float32))
        total = nn.clip_grad_norm_([p], max_norm=1.0)
        assert not np.isfinite(float(total))

    def test_clip_still_scales_and_publishes_gauge(self):
        paddle.set_flags({'FLAGS_tensor_stats': True})
        p = self._param_with_grad(np.array([3.0, 4.0], np.float32))
        total = nn.clip_grad_norm_([p], max_norm=1.0,
                                   error_if_nonfinite=True)
        assert np.isclose(float(total), 5.0)
        assert np.isclose(
            float(np.linalg.norm(np.asarray(p.grad.data))), 1.0,
            rtol=1e-5)
        from paddle_tpu.core import monitor
        g = monitor.metrics().get('ptpu_num_grad_norm_preclip')
        assert g is not None
        assert np.isclose(g.value(site='clip_grad_norm_'), 5.0)

    def test_global_norm_clip_publishes_gauge(self):
        paddle.set_flags({'FLAGS_tensor_stats': True})
        clip = nn.ClipGradByGlobalNorm(clip_norm=1.0)
        p = self._param_with_grad(np.array([0.6, 0.8], np.float32))
        out = clip([(p, p.grad)])
        assert np.isclose(
            float(np.linalg.norm(np.asarray(out[0][1].data))), 1.0,
            rtol=1e-5)
        from paddle_tpu.core import monitor
        g = monitor.metrics().get('ptpu_num_grad_norm_preclip')
        assert np.isclose(g.value(site='global_norm_clip'), 1.0)


class TestGradScaler:
    def _setup(self, grads):
        paddle.seed(0)
        net = nn.Linear(2, len(grads))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for p, g in zip(net.parameters(), grads):
            p.grad = Tensor(np.full(p.shape, g, np.float32))
        return net, opt

    def test_unscale_single_fused_sync_and_found_inf(self):
        from paddle_tpu.amp import GradScaler
        net, opt = self._setup([1.0, np.inf])
        scaler = GradScaler(init_loss_scaling=4.0)
        scaler.unscale_(opt)
        assert scaler._found_inf
        # finite grads are unscaled by 1/scale
        finite = [p for p in net.parameters()
                  if np.isfinite(np.asarray(p.grad.data)).all()]
        assert finite and np.allclose(np.asarray(finite[0].grad.data),
                                      0.25)

    def test_skip_counts_and_scale_gauge(self):
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.core import monitor
        net, opt = self._setup([np.nan, 1.0])
        scaler = GradScaler(init_loss_scaling=8.0,
                            decr_every_n_nan_or_inf=1)
        w_before = np.asarray(net.weight.data).copy()
        scaler.step(opt)
        np.testing.assert_array_equal(np.asarray(net.weight.data),
                                      w_before)       # update skipped
        assert scaler._scale == 4.0                   # backed off
        c = monitor.metrics().get('ptpu_amp_skipped_steps_total')
        assert c is not None and c.value() >= 1
        g = monitor.metrics().get('ptpu_amp_loss_scale')
        assert g.value() == 4.0

    def test_state_dict_round_trip(self):
        from paddle_tpu.amp import GradScaler
        a = GradScaler(init_loss_scaling=512.0, incr_ratio=3.0,
                       decr_ratio=0.25, incr_every_n_steps=7,
                       decr_every_n_nan_or_inf=3)
        a._good_steps, a._bad_steps = 5, 1
        a._scale = 128.0
        sd = json.loads(json.dumps(a.state_dict()))  # checkpoint-ready
        assert sd['incr_count'] == 5 and sd['decr_count'] == 1
        b = GradScaler()
        b.load_state_dict(sd)
        assert b._scale == 128.0
        assert b._incr_ratio == 3.0 and b._decr_ratio == 0.25
        assert b._incr_every_n == 7 and b._decr_every_n == 3
        assert b._good_steps == 5 and b._bad_steps == 1
        assert b.is_use_dynamic_loss_scaling()
        # the restored schedule continues where it left off
        b._found_inf = False
        for _ in range(2):
            b._update()
        assert b._good_steps == 0 and b._scale == 128.0 * 3.0

    def test_legacy_keys_still_accepted(self):
        from paddle_tpu.amp import GradScaler
        b = GradScaler()
        b.set_state_dict({'scale': 64.0, 'good_steps': 2,
                          'bad_steps': 1})
        assert b._scale == 64.0
        assert b._good_steps == 2 and b._bad_steps == 1
