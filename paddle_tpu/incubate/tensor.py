"""paddle.incubate segment ops (operators/segment_pool_op.cc — the
segment_pool op with SUM/MEAN/MAX/MIN pooltypes).

TPU-native: jax.ops.segment_* scatter-reductions — one XLA scatter per
call instead of the reference's sorted-range CPU/CUDA kernels.
`segment_ids` must be sorted ascending (the reference requires the
same); the segment count is taken from the last id + 1, so these are
eager ops (the data-dependent output shape cannot be recorded into a
static program — use them in the input pipeline or dygraph code).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import run_op
from ..ops.common import as_tensor


def _segment(data, segment_ids, kind):
    from ..core.autograd import STATIC_RECORD_HOOK
    if STATIC_RECORD_HOOK is not None:
        raise NotImplementedError(
            f"segment_{kind} has a data-dependent output shape and "
            "cannot be recorded into a static program — call it eagerly")
    data = as_tensor(data)
    ids = as_tensor(segment_ids, ref=data)
    ids_np = np.asarray(ids.data).reshape(-1)
    if ids_np.size == 0:
        raise ValueError("segment_ids must be non-empty")
    if (np.diff(ids_np) < 0).any():
        raise ValueError("segment_ids must be sorted ascending")
    num = int(ids_np[-1]) + 1

    def fn(x, sid):
        sid = sid.reshape(-1)
        if kind == 'sum':
            return jax.ops.segment_sum(x, sid, num_segments=num)
        if kind in ('max', 'min'):
            op = jax.ops.segment_max if kind == 'max' \
                else jax.ops.segment_min
            out = op(x, sid, num_segments=num)
            # empty (gap) segments: the reference's pool buffer is
            # zero-initialized, so they yield 0 — not the scatter
            # identity (+/-inf) jax uses
            return jnp.where(jnp.isfinite(out), out,
                             jnp.zeros((), x.dtype))
        total = jax.ops.segment_sum(x, sid, num_segments=num)
        cnt = jax.ops.segment_sum(jnp.ones_like(sid, x.dtype), sid,
                                  num_segments=num)
        shape = (num,) + (1,) * (x.ndim - 1)
        return total / jnp.maximum(cnt.reshape(shape), 1)
    return run_op(f'segment_{kind}', fn, [data, ids], n_nondiff=1)


def segment_sum(data, segment_ids, name=None):
    """paddle.incubate.segment_sum."""
    return _segment(data, segment_ids, 'sum')


def segment_mean(data, segment_ids, name=None):
    """paddle.incubate.segment_mean."""
    return _segment(data, segment_ids, 'mean')


def segment_max(data, segment_ids, name=None):
    """paddle.incubate.segment_max (empty segments yield 0 like the
    reference's pool init, not -inf)."""
    out = _segment(data, segment_ids, 'max')
    return out


def segment_min(data, segment_ids, name=None):
    """paddle.incubate.segment_min."""
    return _segment(data, segment_ids, 'min')
