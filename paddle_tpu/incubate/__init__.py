"""paddle_tpu.incubate (parity: python/paddle/incubate)."""
from . import optimizer
