"""paddle_tpu.incubate (parity: python/paddle/incubate)."""
from . import optimizer
from . import asp
from . import checkpoint
from .optimizer import LookAhead, ModelAverage

from . import tensor
from .tensor import (segment_sum, segment_mean, segment_max, segment_min)
