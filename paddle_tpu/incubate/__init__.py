"""paddle_tpu.incubate (parity: python/paddle/incubate)."""
from . import optimizer
from . import asp
from . import checkpoint
from .optimizer import LookAhead, ModelAverage
