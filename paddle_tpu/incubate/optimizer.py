"""Incubate optimizers: LookAhead, ModelAverage (parity:
python/paddle/incubate/optimizer)."""
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..optimizer import Optimizer


class LookAhead(Optimizer):
    """Parity: incubate/optimizer/lookahead.py — k fast steps then slow-weight
    interpolation."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._k_count = 0
        self._parameter_list = inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            for p in self._parameter_list:
                key = p.name or str(id(p))
                slow = self._slow.get(key)
                if slow is None:
                    slow = p.data
                slow = slow + self.alpha * (p.data - slow)
                self._slow[key] = slow
                p.data = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def set_state_dict(self, sd):
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage(Optimizer):
    """Parity: incubate/optimizer/modelaverage.py — running average of params
    applied at eval time."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        super().__init__(0.0, parameters)
        self._sum = {}
        self._count = 0
        self._saved = None

    def step(self):
        self._count += 1
        for p in self._parameter_list or []:
            key = p.name or str(id(p))
            self._sum[key] = self._sum.get(key, 0) + p.data

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            saved = [(p, p.data) for p in self._parameter_list or []]
            for p in self._parameter_list or []:
                key = p.name or str(id(p))
                if key in self._sum and self._count:
                    p.data = (self._sum[key] / self._count).astype(p.dtype)
            try:
                yield
            finally:
                if need_restore:
                    for p, d in saved:
                        p.data = d
        return ctx()

    def restore(self, executor=None):
        pass
