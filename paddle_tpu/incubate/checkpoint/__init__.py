from .auto_checkpoint import (AutoCheckpointChecker, TrainEpochRange,
                              train_epoch_range)
