"""Auto-checkpoint.

Reference parity: fluid/incubate/checkpoint/auto_checkpoint.py —
AutoCheckpointChecker:71 (env-driven enablement), TrainEpochRange:265 (wraps
the epoch loop; serializes state each epoch with epoch_no metadata; restores
on restart) + checkpoint_saver.py CheckpointSaver. The reference stores to
HDFS via PaddleCloud env; here the FS abstraction (fleet.utils.fs LocalFS /
HDFSClient) backs it, keyed by the same env names so job-platform wiring
carries over.
"""
import json
import os
import time

from ... import framework
from ...distributed.fleet.utils.fs import LocalFS


class AutoCheckpointChecker:
    """Parity: auto_checkpoint.py:71 — env-driven config."""

    def __init__(self):
        self.run_env = os.environ.get('PADDLE_RUNNING_ENV', '')
        self.platform = os.environ.get('PADDLE_RUNNING_PLATFORM', '')
        self.job_id = os.environ.get('PADDLE_JOB_ID', '')
        self.hdfs_home = os.environ.get('PADDLE_EDL_HDFS_HOME', '')
        self.checkpoint_dir = os.environ.get(
            'PADDLE_EDL_HDFS_CHECKPOINT_PATH',
            os.environ.get('PADDLE_CHECKPOINT_DIR', ''))
        self.save_checkpoint_inter = int(os.environ.get(
            'PADDLE_EDL_SAVE_CHECKPOINT_INTER', '900'))

    def get_range_checkpoint_path(self, name):
        return os.path.join(self.checkpoint_dir, self.job_id or 'job',
                            'range', name)

    @property
    def valid(self):
        return bool(self.checkpoint_dir)


class CheckpointSaver:
    """Parity: checkpoint_saver.py — numbered checkpoint dirs with metadata,
    keep-last semantics."""

    def __init__(self, fs=None):
        self.fs = fs or LocalFS()

    def save_checkpoint(self, path, state, epoch_no, max_keep=3):
        import tempfile
        self.fs.mkdirs(path)
        ckpt_dir = os.path.join(path, f"__paddle_checkpoint__{epoch_no}")
        self.fs.mkdirs(ckpt_dir)
        local = isinstance(self.fs, LocalFS)
        stage = ckpt_dir if local else tempfile.mkdtemp()
        framework.save(state, os.path.join(stage, 'state.pdparams'))
        meta = {'epoch_no': epoch_no, 'time': time.time()}
        with open(os.path.join(stage, 'meta.json'), 'w') as f:
            json.dump(meta, f)
        if not local:
            # remote FS: stage locally then upload through the abstraction
            self.fs.upload(os.path.join(stage, 'state.pdparams'),
                           os.path.join(ckpt_dir, 'state.pdparams'))
            self.fs.upload(os.path.join(stage, 'meta.json'),
                           os.path.join(ckpt_dir, 'meta.json'))
        # prune old
        dirs, _ = self.fs.ls_dir(path)
        nums = sorted(int(d.rsplit('__', 1)[-1]) for d in dirs
                      if d.startswith('__paddle_checkpoint__'))
        for n in nums[:-max_keep]:
            self.fs.delete(os.path.join(path, f"__paddle_checkpoint__{n}"))
        return ckpt_dir

    def load_checkpoint(self, path):
        if not self.fs.is_exist(path):
            return None, -1
        dirs, _ = self.fs.ls_dir(path)
        nums = sorted(int(d.rsplit('__', 1)[-1]) for d in dirs
                      if d.startswith('__paddle_checkpoint__'))
        if not nums:
            return None, -1
        latest = os.path.join(path, f"__paddle_checkpoint__{nums[-1]}")
        if isinstance(self.fs, LocalFS):
            stage = latest
        else:
            import tempfile
            stage = tempfile.mkdtemp()
            self.fs.download(os.path.join(latest, 'state.pdparams'),
                             os.path.join(stage, 'state.pdparams'))
            self.fs.download(os.path.join(latest, 'meta.json'),
                             os.path.join(stage, 'meta.json'))
        state = framework.load(os.path.join(stage, 'state.pdparams'))
        with open(os.path.join(stage, 'meta.json')) as f:
            meta = json.load(f)
        return state, meta['epoch_no']


class TrainEpochRange:
    """Parity: auto_checkpoint.py TrainEpochRange:265 — iterate epochs,
    skipping already-completed ones after a restart and saving state at each
    epoch end.

        r = TrainEpochRange(10, 'job1', model=model, optimizer=opt)
        for epoch in r.get():
            ... train ...
    """

    def __init__(self, max_epoch_num, name, model=None, optimizer=None,
                 checkpoint_dir=None, save_checkpoint_inter=0):
        self.save_checkpoint_inter = save_checkpoint_inter
        self._last_save_time = 0.0
        self.max_epoch_num = max_epoch_num
        self.name = name
        self.model = model
        self.optimizer = optimizer
        self.checker = AutoCheckpointChecker()
        base = checkpoint_dir or self.checker.checkpoint_dir or '/tmp/acp'
        self.path = os.path.join(base, name)
        self.saver = CheckpointSaver()
        self._restored_epoch = -1
        state, epoch_no = self.saver.load_checkpoint(self.path)
        if state is not None:
            self._restored_epoch = epoch_no
            if self.model is not None and 'model' in state:
                self.model.set_state_dict(state['model'])
            if self.optimizer is not None and 'optimizer' in state:
                self.optimizer.set_state_dict(state['optimizer'])

    def get(self):
        start = self._restored_epoch + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            # throttle by wall time (parity: PADDLE_EDL_SAVE_CHECKPOINT_INTER)
            # but always persist the final epoch
            due = (time.time() - self._last_save_time
                   >= self.save_checkpoint_inter)
            if due or epoch == self.max_epoch_num - 1:
                self.save(epoch)
                self._last_save_time = time.time()

    def save(self, epoch_no):
        state = {}
        if self.model is not None:
            state['model'] = self.model.state_dict()
        if self.optimizer is not None:
            state['optimizer'] = self.optimizer.state_dict()
        self.saver.save_checkpoint(self.path, state, epoch_no)

    @property
    def restored_from(self):
        return self._restored_epoch


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None, name='acp',
                      **kwargs):
    """Parity: the module-level helper used inside Executor.run's hook."""
    r = TrainEpochRange(max_epoch_num, name, **kwargs)
    yield from r.get()
