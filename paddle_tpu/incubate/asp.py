"""ASP — 2:4 structured sparsity.

Reference parity: fluid/contrib/sparsity/asp.py — decorate(optimizer):55,
prune_model:95, ASPHelper:214 (generate 2:4 masks per supported weight and
re-apply the mask after every optimizer step via an appended elementwise
multiply). TPU note: 2:4 sparse matmul acceleration is an Ampere-TensorCore
feature without an MXU analogue, so here ASP provides the ALGORITHMIC side
(mask generation, mask maintenance through training) — the reference's
accuracy-preserving pruning workflow — with dense execution.
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


def _mask_n_m_numpy(w, n=2, m=4):
    """Keep the n largest-magnitude entries of every m along the last
    axis."""
    shape = w.shape
    flat = w.reshape(-1, shape[-1])
    cols = shape[-1] - shape[-1] % m
    mask = np.ones_like(flat, dtype=np.float32)
    if cols:
        blocks = flat[:, :cols].reshape(flat.shape[0], -1, m)
        order = np.argsort(np.abs(blocks), axis=-1)
        bm = np.ones_like(blocks, dtype=np.float32)
        np.put_along_axis(bm, order[..., :m - n], 0.0, axis=-1)
        mask[:, :cols] = bm.reshape(flat.shape[0], cols)
    return mask.reshape(shape)


def create_mask(tensor, func_name='mask_2d_best', n=2, m=4):
    """Parity: sparsity.create_mask."""
    w = np.asarray(tensor.data if isinstance(tensor, Tensor) else tensor)
    return Tensor(_mask_n_m_numpy(w, n, m))


def check_sparsity(tensor, n=2, m=4):
    w = np.asarray(tensor.data if isinstance(tensor, Tensor) else tensor,
                   dtype=np.float32)
    cols = w.shape[-1] - w.shape[-1] % m
    if cols == 0:
        return True
    blocks = np.abs(w[..., :cols].reshape(-1, m))
    nz = (blocks != 0).sum(-1)
    return bool((nz <= n).all())


class ASPHelper:
    """Parity: asp.py ASPHelper:214."""

    _masks = {}

    @classmethod
    def _supported(cls, p):
        return len(p.shape) == 2 and p.shape[0] >= 4 and p.shape[1] >= 4

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo='mask_1d'):
        for name, p in model.named_parameters():
            if not cls._supported(p) or p.stop_gradient:
                continue
            mask = jnp.asarray(_mask_n_m_numpy(np.asarray(p.data), n, m),
                               p.data.dtype)  # keep param dtype (bf16 safe)
            cls._masks[name if p.name is None else p.name] = mask
            p.data = p.data * mask
        return cls._masks

    @classmethod
    def apply_masks(cls, model):
        for name, p in model.named_parameters():
            key = name if p.name is None else p.name
            if key in cls._masks:
                p.data = p.data * cls._masks[key]


def prune_model(model, n=2, m=4, mask_algo='mask_1d', with_mask=True):
    """Parity: sparsity.prune_model:95."""
    return ASPHelper.prune_model(model, n, m, mask_algo)


class _ASPOptimizerWrapper:
    """Re-applies masks after every step (parity: the appended
    elementwise_mul ops)."""

    def __init__(self, optimizer, model=None):
        self._inner = optimizer
        self._model = model

    def step(self):
        self._inner.step()
        if self._model is not None:
            ASPHelper.apply_masks(self._model)
        else:
            for p in self._inner._parameter_list or []:
                key = p.name
                if key in ASPHelper._masks:
                    p.data = p.data * ASPHelper._masks[key]

    def minimize(self, loss, *a, **k):
        out = self._inner.minimize(loss, *a, **k)
        self.step_masks_only()
        return out

    def step_masks_only(self):
        for p in self._inner._parameter_list or []:
            key = p.name
            if key in ASPHelper._masks:
                p.data = p.data * ASPHelper._masks[key]

    def __getattr__(self, item):
        return getattr(self.__dict__['_inner'], item)


def decorate(optimizer, model=None):
    """Parity: sparsity.decorate(optimizer):55."""
    return _ASPOptimizerWrapper(optimizer, model)
