"""Top-level API sheet remainder (python/paddle/__init__.py __all__ +
static/vision/jit/distributed tails). Each name is a thin adapter over
the modern surface; device-specific Places exist for API compatibility
(PJRT owns real placement — SURVEY N1 disposition).
"""
import numpy as np
import jax
import jax.numpy as jnp

from .core.tensor import Tensor, inplace_rebind
from .ops.common import as_tensor


def add_n(inputs, name=None):
    """paddle.add_n — elementwise sum of a tensor list
    (operators/sum_op.cc)."""
    from .core.autograd import run_op
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    tens = [as_tensor(t) for t in inputs]

    def fn(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return run_op('add_n', fn, tens)


def floor_mod(x, y, name=None):
    """paddle.floor_mod — alias of mod (operators/elementwise_mod)."""
    from .ops.math import mod
    return mod(x, y)


def inverse(x, name=None):
    """paddle.inverse (operators/inverse_op.cc)."""
    from .core.autograd import run_op
    return run_op('inverse', jnp.linalg.inv, [as_tensor(x)])


def t(input, name=None):
    """paddle.t — transpose a 0/1/2-D tensor (operators/transpose)."""
    x = as_tensor(input)
    if len(x.shape) > 2:
        raise ValueError(
            f"paddle.t expects ndim <= 2, got {len(x.shape)}; use "
            "paddle.transpose for higher ranks")
    if len(x.shape) < 2:
        return x
    from .ops.manip import transpose
    return transpose(x, [1, 0])


def is_tensor(x):
    """paddle.is_tensor."""
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    """paddle.is_empty (operators/is_empty_op.cc)."""
    return Tensor(jnp.asarray(int(np.prod(as_tensor(x).shape)) == 0))


def rank(input):
    """paddle.rank — number of dimensions as a 0-D tensor."""
    return Tensor(jnp.asarray(len(as_tensor(input).shape), jnp.int32))


def reverse(x, axis, name=None):
    """paddle.reverse (operators/reverse_op.cc)."""
    from .ops.manip import flip
    return flip(x, [axis] if isinstance(axis, int) else axis)


def scatter_(x, index, updates, overwrite=True, name=None):
    """paddle.scatter_ — the in-place spelling; JAX arrays are
    immutable, so this rebinds the tensor's buffer to the scattered
    result (the caller-visible contract matches: x reflects the
    update)."""
    from .ops.manip import scatter
    out = scatter(x, index, updates, overwrite=overwrite)
    return inplace_rebind(x, out)


_print_options = {'precision': 8, 'threshold': 1000, 'edgeitems': 3,
                  'linewidth': 80}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions — forwards to numpy (tensors repr via
    numpy arrays)."""
    kw = {}
    if precision is not None:
        kw['precision'] = precision
        _print_options['precision'] = precision
    if threshold is not None:
        kw['threshold'] = threshold
    if edgeitems is not None:
        kw['edgeitems'] = edgeitems
    if linewidth is not None:
        kw['linewidth'] = linewidth
    if sci_mode is not None:
        kw['suppress'] = not sci_mode
    np.set_printoptions(**kw)


def batch(reader, batch_size, drop_last=False):
    """paddle.batch — the classic reader decorator (superseded by
    DataLoader, kept for ported training scripts)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def get_cuda_rng_state():
    """paddle.get_cuda_rng_state — maps to the functional RNG stream
    (no CUDA here; one device-agnostic state)."""
    from .core import rng
    return rng.get_rng_state()


def set_cuda_rng_state(state):
    """paddle.set_cuda_rng_state — see get_cuda_rng_state."""
    from .core import rng
    rng.set_rng_state(state)


class CUDAPinnedPlace:
    """API-compat place (PJRT owns placement; pinned-host memory is a
    jax memory-kind concern, not a place)."""

    def __repr__(self):
        return 'CUDAPinnedPlace'


class NPUPlace:
    """API-compat place for ported scripts; maps to the single
    accelerator PJRT exposes."""

    def __init__(self, id=0):
        self.id = id

    def __repr__(self):
        return f'NPUPlace({self.id})'


def cholesky(x, upper=False, name=None):
    """paddle.cholesky — top-level alias of linalg.cholesky."""
    from .ops.linalg import cholesky as _c
    return _c(x, upper=upper)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.create_parameter (static-graph parameter helper)."""
    from .static.api_tail import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def check_shape(shape):
    """paddle.check_shape — validate a shape argument (utils.check
    parity: ints or -1 placeholders)."""
    for d in (shape if isinstance(shape, (list, tuple)) else [shape]):
        if not isinstance(d, (int, np.integer)) or (d < -1):
            raise ValueError(f"invalid dim {d!r} in shape {shape}")
    return True


def tanh_(x, name=None):
    """paddle.tanh_ — value-returning inplace spelling (JAX buffers are
    immutable; the tensor rebinds)."""
    from .ops.math import tanh
    out = tanh(x)
    return inplace_rebind(x, out)


def reshape_(x, shape, name=None):
    """paddle.reshape_ — inplace spelling of reshape."""
    from .ops.manip import reshape
    out = reshape(x, shape)
    return inplace_rebind(x, out)


def squeeze_(x, axis=None, name=None):
    """paddle.squeeze_ — inplace spelling of squeeze."""
    from .ops.manip import squeeze
    out = squeeze(x, axis)
    return inplace_rebind(x, out)


def unsqueeze_(x, axis, name=None):
    """paddle.unsqueeze_ — inplace spelling of unsqueeze."""
    from .ops.manip import unsqueeze
    out = unsqueeze(x, axis)
    return inplace_rebind(x, out)
