"""PS worker/server runtime.

Reference parity: TheOnePSRuntime's worker/server lifecycle over the
service tier (BrpcPsServer/Client → distributed/ps/service.py).
Table configs come from `strategy.sparse_table_configs`-style dicts
(set_table_configs / the_one_ps _get_fleet_proto analogue), the env
(PADDLE_PS_TABLES — either the legacy "id:dim:opt,..." or a JSON list of
TableParameter dicts), or defaults; server endpoint from
PADDLE_CURRENT_ENDPOINT.
"""
import dataclasses
import json
import os

# programmatic table configs (list of TableParameter dicts); takes
# precedence over the env (parity: the_one_ps builds table protos from
# the DistributedStrategy, the env is the launch-time channel)
_TABLE_CONFIGS = None

_OPTIMIZERS = ('sgd', 'adagrad', 'adam')


@dataclasses.dataclass
class TableParameter:
    """Typed table config (parity: ps.proto TableParameter +
    CtrCommonAccessor hypers built by the_one_ps._get_fleet_proto:434 —
    a misspelled key or out-of-range hyper fails HERE, at configuration
    time, not as a garbage table on the server)."""
    table_id: int
    embedx_dim: int
    optimizer: str = 'adagrad'
    init_range: float = 0.05
    shard_num: int = 16
    seed: int = 0
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    ssd_path: str = None
    mem_budget_rows: int = 1 << 20

    def __post_init__(self):
        if not isinstance(self.table_id, int) or self.table_id < 0:
            raise ValueError(f"table_id must be a non-negative int, got "
                             f"{self.table_id!r}")
        if not isinstance(self.embedx_dim, int) or self.embedx_dim <= 0:
            raise ValueError(f"embedx_dim must be a positive int, got "
                             f"{self.embedx_dim!r}")
        if self.optimizer not in _OPTIMIZERS:
            raise ValueError(f"optimizer must be one of {_OPTIMIZERS}, "
                             f"got {self.optimizer!r}")
        if not (0.0 <= self.init_range <= 10.0):
            raise ValueError(f"init_range out of range: {self.init_range}")
        if self.shard_num <= 0:
            raise ValueError(f"shard_num must be positive: "
                             f"{self.shard_num}")
        for name in ('beta1', 'beta2'):
            v = getattr(self, name)
            if not (0.0 <= v < 1.0):
                raise ValueError(f"{name} must be in [0, 1): {v}")
        if self.eps <= 0:
            raise ValueError(f"eps must be positive: {self.eps}")
        if self.mem_budget_rows <= 0:
            raise ValueError(f"mem_budget_rows must be positive: "
                             f"{self.mem_budget_rows}")
        if self.ssd_path is not None and not isinstance(self.ssd_path,
                                                        str):
            raise ValueError("ssd_path must be a path string")

    @classmethod
    def from_dict(cls, d):
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - fields
        if unknown:
            raise ValueError(
                f"unknown table config keys: {sorted(unknown)}; "
                f"known: {sorted(fields)}")
        missing = {'table_id', 'embedx_dim'} - set(d)
        if missing:
            raise ValueError(f"table config needs {sorted(missing)}")
        return cls(**d)

    def to_dict(self):
        out = dataclasses.asdict(self)
        if out['ssd_path'] is None:
            out.pop('ssd_path')
        return out


def set_table_configs(configs):
    """configs: list of TableParameter instances or dicts (validated
    through TableParameter — parity: ps.proto TableParameter +
    accessor)."""
    global _TABLE_CONFIGS
    if not configs:
        _TABLE_CONFIGS = None
        return
    parsed = [c if isinstance(c, TableParameter)
              else TableParameter.from_dict(c) for c in configs]
    ids = [c.table_id for c in parsed]
    if len(set(ids)) != len(ids):
        raise ValueError(f"duplicate table_id in configs: {ids}")
    _TABLE_CONFIGS = parsed


def _table_configs():
    """→ list of validated table-config dicts."""
    if _TABLE_CONFIGS is not None:
        return [c.to_dict() for c in _TABLE_CONFIGS]
    spec = os.environ.get('PADDLE_PS_TABLES', '0:16:adagrad')
    if spec.lstrip().startswith('['):
        # validate on every call — the env is a launch-time channel
        return [TableParameter.from_dict(c).to_dict()
                for c in json.loads(spec)]
    out = []
    for part in spec.split(','):
        tid, dim, opt = part.split(':')
        out.append(TableParameter(table_id=int(tid),
                                  embedx_dim=int(dim),
                                  optimizer=opt).to_dict())
    return out


class _Worker:
    def __init__(self, fleet_obj):
        self.fleet = fleet_obj
        self.client = None
        eps = fleet_obj.server_endpoints() if fleet_obj._role_maker else []
        if eps:
            from .service import PsClient
            self.client = PsClient(eps)

    def stop(self):
        if self.client is not None:
            self.client.close()


class _Server:
    def __init__(self, fleet_obj):
        from .service import PsServer
        ep = os.environ.get('PADDLE_CURRENT_ENDPOINT', '0.0.0.0:0')
        port = int(ep.rsplit(':', 1)[1]) if ':' in ep else 0
        # durable push-dedup high-water mark (at-most-once across server
        # restart) when a state dir is provided at launch; namespaced by
        # endpoint — launchers export one env to every rank, and shard
        # servers must NOT share dedup marks (a mark recovered from a
        # co-hosted peer would drop this shard's legitimate replay)
        state = os.environ.get('PADDLE_PS_STATE_DIR')
        if state:
            state = os.path.join(state, ep.replace(':', '_'))
        self.server = PsServer(port=port, state_dir=state)
        for cfg in _table_configs():
            c = dict(cfg)
            tid = c.pop('table_id')
            dim = c.pop('embedx_dim')
            self.server.add_table(tid, dim, **c)

    def run(self):
        self.server.run()


def get_or_create_worker(fleet_obj):
    return _Worker(fleet_obj)


def get_or_create_server(fleet_obj):
    return _Server(fleet_obj)
