"""PS worker/server runtime (detailed implementation in ps/tables.py —
reference: BrpcPsClient/Server, Communicator:197)."""


class _Worker:
    def __init__(self, fleet_obj):
        self.fleet = fleet_obj

    def stop(self):
        pass


class _Server:
    def __init__(self, fleet_obj):
        self.fleet = fleet_obj

    def run(self):
        raise NotImplementedError(
            "standalone PS server process lands with distributed/ps/tables")


def get_or_create_worker(fleet_obj):
    return _Worker(fleet_obj)


def get_or_create_server(fleet_obj):
    return _Server(fleet_obj)
