"""PS worker/server runtime.

Reference parity: TheOnePSRuntime's worker/server lifecycle over the
service tier (BrpcPsServer/Client → distributed/ps/service.py).
Table configs come from env (PADDLE_PS_TABLES="id:dim:opt,...") or
defaults; server endpoint from PADDLE_CURRENT_ENDPOINT.
"""
import os


def _table_configs():
    spec = os.environ.get('PADDLE_PS_TABLES', '0:16:adagrad')
    out = []
    for part in spec.split(','):
        tid, dim, opt = part.split(':')
        out.append((int(tid), int(dim), opt))
    return out


class _Worker:
    def __init__(self, fleet_obj):
        self.fleet = fleet_obj
        self.client = None
        eps = fleet_obj.server_endpoints() if fleet_obj._role_maker else []
        if eps:
            from .service import PsClient
            self.client = PsClient(eps)

    def stop(self):
        if self.client is not None:
            self.client.close()


class _Server:
    def __init__(self, fleet_obj):
        from .service import PsServer
        ep = os.environ.get('PADDLE_CURRENT_ENDPOINT', '0.0.0.0:0')
        port = int(ep.rsplit(':', 1)[1]) if ':' in ep else 0
        self.server = PsServer(port=port)
        for tid, dim, opt in _table_configs():
            self.server.add_table(tid, dim, optimizer=opt)

    def run(self):
        self.server.run()


def get_or_create_worker(fleet_obj):
    return _Worker(fleet_obj)


def get_or_create_server(fleet_obj):
    return _Server(fleet_obj)
