"""PS worker/server runtime.

Reference parity: TheOnePSRuntime's worker/server lifecycle over the
service tier (BrpcPsServer/Client → distributed/ps/service.py).
Table configs come from `strategy.sparse_table_configs`-style dicts
(set_table_configs / the_one_ps _get_fleet_proto analogue), the env
(PADDLE_PS_TABLES — either the legacy "id:dim:opt,..." or a JSON list of
TableParameter dicts), or defaults; server endpoint from
PADDLE_CURRENT_ENDPOINT.
"""
import json
import os

# programmatic table configs (list of TableParameter dicts); takes
# precedence over the env (parity: the_one_ps builds table protos from
# the DistributedStrategy, the env is the launch-time channel)
_TABLE_CONFIGS = None

_TABLE_KEYS = {'table_id', 'embedx_dim', 'optimizer', 'init_range',
               'shard_num', 'seed', 'beta1', 'beta2', 'eps', 'ssd_path',
               'mem_budget_rows'}


def set_table_configs(configs):
    """configs: list of dicts with keys table_id, embedx_dim, optimizer,
    and optionally init_range/shard_num/seed/beta1/beta2/eps/ssd_path/
    mem_budget_rows (parity: ps.proto TableParameter + accessor)."""
    global _TABLE_CONFIGS
    for c in configs or []:
        unknown = set(c) - _TABLE_KEYS
        if unknown:
            raise ValueError(f"unknown table config keys: {unknown}")
        if 'table_id' not in c or 'embedx_dim' not in c:
            raise ValueError("table config needs table_id and embedx_dim")
    _TABLE_CONFIGS = list(configs) if configs else None


def _table_configs():
    """→ list of TableParameter dicts."""
    if _TABLE_CONFIGS is not None:
        return list(_TABLE_CONFIGS)
    spec = os.environ.get('PADDLE_PS_TABLES', '0:16:adagrad')
    if spec.lstrip().startswith('['):
        cfgs = json.loads(spec)
        for c in cfgs:            # validate without caching — the env is
            unknown = set(c) - _TABLE_KEYS   # re-read on every call
            if unknown:
                raise ValueError(f"unknown table config keys: {unknown}")
        return cfgs
    out = []
    for part in spec.split(','):
        tid, dim, opt = part.split(':')
        out.append({'table_id': int(tid), 'embedx_dim': int(dim),
                    'optimizer': opt})
    return out


class _Worker:
    def __init__(self, fleet_obj):
        self.fleet = fleet_obj
        self.client = None
        eps = fleet_obj.server_endpoints() if fleet_obj._role_maker else []
        if eps:
            from .service import PsClient
            self.client = PsClient(eps)

    def stop(self):
        if self.client is not None:
            self.client.close()


class _Server:
    def __init__(self, fleet_obj):
        from .service import PsServer
        ep = os.environ.get('PADDLE_CURRENT_ENDPOINT', '0.0.0.0:0')
        port = int(ep.rsplit(':', 1)[1]) if ':' in ep else 0
        # durable push-dedup high-water mark (at-most-once across server
        # restart) when a state dir is provided at launch; namespaced by
        # endpoint — launchers export one env to every rank, and shard
        # servers must NOT share dedup marks (a mark recovered from a
        # co-hosted peer would drop this shard's legitimate replay)
        state = os.environ.get('PADDLE_PS_STATE_DIR')
        if state:
            state = os.path.join(state, ep.replace(':', '_'))
        self.server = PsServer(port=port, state_dir=state)
        for cfg in _table_configs():
            c = dict(cfg)
            tid = c.pop('table_id')
            dim = c.pop('embedx_dim')
            self.server.add_table(tid, dim, **c)

    def run(self):
        self.server.run()


def get_or_create_worker(fleet_obj):
    return _Worker(fleet_obj)


def get_or_create_server(fleet_obj):
    return _Server(fleet_obj)
