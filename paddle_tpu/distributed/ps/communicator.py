"""Async PS communicator — decouple trainer compute from PS RPCs.

Reference parity: fluid/distributed/service/communicator.h:197
(AsyncCommunicator: background send/recv threads + bounded queues so the
trainer never blocks on the wire) and communicator.cc's batch-merged
push. TPU-native shape: the overlap that matters on a tunneled chip is
host<->device as much as host<->PS, so the communicator pairs

  * a PULL prefetcher: `pull_ahead(feed)` walks the id stream in a
    worker thread and keeps up to `depth` pulled (and optionally
    device-put) embedding batches ready, and
  * a PUSH drainer: `push_async(ids, grads, lr)` enqueues the (possibly
    still in-flight jax array) gradient; the worker forces the readback
    and sends — so the device never waits for the push wire time, and
    the readback of step t overlaps the compute of step t+1.

Staleness contract matches the reference's async mode: a pull issued at
step t+depth may miss pushes still queued from steps < t; `flush()` is
the communicator's barrier (reference Communicator::Clean + the sync-
mode fences).
"""
import queue
import threading

import numpy as np

__all__ = ['AsyncCommunicator']


class _Stop:
    pass


class AsyncCommunicator:
    def __init__(self, client, table_id, dim, depth=2, device_put=None):
        """client: PsClient (thread-safe). depth: max in-flight pulled
        batches / unsent pushes. device_put: optional fn(np_rows) ->
        device array run inside the prefetch thread, so H2D upload of
        batch t+1 overlaps compute of batch t."""
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(dim)
        self.depth = int(depth)
        self._device_put = device_put
        self._pull_out = queue.Queue(self.depth)
        self._push_q = queue.Queue(self.depth)
        self._push_err = None
        self._pushed = threading.Event()
        self._push_thread = threading.Thread(target=self._push_loop,
                                             daemon=True)
        self._push_thread.start()
        self._pull_thread = None

    # -- pull side -----------------------------------------------------------
    def pull_ahead(self, id_batches):
        """Start prefetching: `id_batches` is an iterable of int64 id
        arrays. Returns an iterator of (ids, rows) in order, at most
        `depth` batches ahead of the consumer."""
        if self._pull_thread is not None:
            raise RuntimeError("pull_ahead already active; exhaust the "
                               "previous iterator first")
        out = self._pull_out

        def loop():
            try:
                for ids in id_batches:
                    # shape is the client's contract (PsClient.pull
                    # flattens; a chunk adapter may keep [K, rows])
                    ids = np.ascontiguousarray(ids, np.int64)
                    rows = self.client.pull(self.table_id, ids, self.dim)
                    if self._device_put is not None:
                        rows = self._device_put(rows)
                    out.put((ids, rows))
            except Exception as e:           # surfaced at the consumer
                out.put(e)
            finally:
                out.put(_Stop)

        self._pull_thread = threading.Thread(target=loop, daemon=True)
        self._pull_thread.start()

        def results():
            while True:
                item = out.get()
                if item is _Stop:
                    self._pull_thread = None
                    return
                if isinstance(item, Exception):
                    self._pull_thread = None
                    raise item
                yield item
        return results()

    # -- push side -----------------------------------------------------------
    def push_async(self, ids, grads, lr):
        """Queue a gradient push and return immediately. `grads` may be
        a live jax array — the worker thread forces it, so device->host
        readback overlaps the caller's next dispatch. Raises any error
        from a PREVIOUS push (at-most-depth delayed, never silent)."""
        if self._push_err is not None:
            err, self._push_err = self._push_err, None
            raise err
        self._push_q.put((ids, grads, float(lr)))

    def _push_loop(self):
        while True:
            item = self._push_q.get()
            if item is _Stop:
                return
            ids, grads, lr = item
            try:
                g = np.asarray(grads)        # forces device readback
                self.client.push(self.table_id, ids, g, lr)
            except Exception as e:           # noqa: BLE001
                self._push_err = e
            finally:
                self._push_q.task_done()

    def flush(self):
        """Barrier: wait until every queued push has landed on the
        servers (reference sync-mode fence). Re-raises a push error."""
        self._push_q.join()
        if self._push_err is not None:
            err, self._push_err = self._push_err, None
            raise err

    def stop(self):
        self.flush()
        self._push_q.put(_Stop)
        self._push_thread.join(timeout=10)
