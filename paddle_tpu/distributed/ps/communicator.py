"""Async PS communicator — decouple trainer compute from PS RPCs.

Reference parity: fluid/distributed/service/communicator.h:197
(AsyncCommunicator: background send/recv threads + bounded queues so the
trainer never blocks on the wire) and communicator.cc's batch-merged
push. TPU-native shape: the overlap that matters on a tunneled chip is
host<->device as much as host<->PS, so the communicator pairs

  * a PULL prefetcher: `pull_ahead(feed)` walks the id stream in a
    worker thread and keeps up to `depth` pulled (and optionally
    device-put) embedding batches ready, and
  * a PUSH drainer: `push_async(ids, grads, lr)` enqueues the (possibly
    still in-flight jax array) gradient; the worker forces the readback
    and sends — so the device never waits for the push wire time, and
    the readback of step t overlaps the compute of step t+1.

Staleness contract matches the reference's async mode: a pull issued at
step t+depth may miss pushes still queued from steps < t; `flush()` is
the communicator's barrier (reference Communicator::Clean + the sync-
mode fences).
"""
import queue
import threading
import time

import numpy as np

__all__ = ['AsyncCommunicator']


class _Stop:
    pass


class AsyncCommunicator:
    def __init__(self, client, table_id, dim, depth=2, device_put=None):
        """client: PsClient (thread-safe). depth: max in-flight pulled
        batches / unsent pushes. device_put: optional fn(np_rows) ->
        device array run inside the prefetch thread, so H2D upload of
        batch t+1 overlaps compute of batch t."""
        self.client = client
        self.table_id = int(table_id)
        self.dim = int(dim)
        self.depth = int(depth)
        self._device_put = device_put
        self._push_q = queue.Queue(self.depth)
        self._push_err = None
        self._pushed = threading.Event()
        self._push_thread = threading.Thread(target=self._push_loop,
                                             daemon=True)
        self._push_thread.start()
        self._pull_thread = None
        self._cur_pull = None     # (stop_event, thread, queue) of the
                                  # ACTIVE pull — cancellation is
                                  # per-generation, so a stale abandoned
                                  # iterator can't kill a newer pull

    # -- pull side -----------------------------------------------------------
    def pull_ahead(self, id_batches):
        """Start prefetching: `id_batches` is an iterable of int64 id
        arrays. Returns an iterator of (ids, rows) in order, at most
        `depth` batches ahead of the consumer."""
        if self._pull_thread is not None:
            raise RuntimeError("pull_ahead already active; exhaust, "
                               "close() or cancel_pull() the previous "
                               "iterator first")
        out = queue.Queue(self.depth)     # per-pull: never shared across
        stop = threading.Event()          # generations

        def _put(item):
            """Bounded put that gives up when the consumer cancelled —
            an abandoned iterator must not wedge this thread forever on
            a full queue."""
            while not stop.is_set():
                try:
                    out.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def loop():
            try:
                for ids in id_batches:
                    if stop.is_set():
                        return
                    # shape is the client's contract (PsClient.pull
                    # flattens; a chunk adapter may keep [K, rows])
                    ids = np.ascontiguousarray(ids, np.int64)
                    rows = self.client.pull(self.table_id, ids, self.dim)
                    if self._device_put is not None:
                        rows = self._device_put(rows)
                    if not _put((ids, rows)):
                        return
            except Exception as e:           # surfaced at the consumer
                _put(e)
            finally:
                _put(_Stop)

        t = threading.Thread(target=loop, daemon=True)
        self._pull_thread = t
        self._cur_pull = (stop, t, out)
        t.start()

        def results():
            try:
                while True:
                    item = out.get()
                    if item is _Stop:
                        return
                    if isinstance(item, Exception):
                        raise item
                    yield item
            finally:
                # normal exhaustion, an error, or an abandoned iterator
                # (GeneratorExit lands here) all release THIS pull's
                # producer — a newer generation is untouched
                self._cancel_generation(stop, t, out)

        return results()

    def _cancel_generation(self, stop, t, out):
        """Stop one pull generation's producer and release its slot
        (only if it still owns the slot). Idempotent. Bounded wait: a
        producer stuck in an in-flight client.pull() RPC (dead server,
        partition) can't be interrupted — after the deadline the daemon
        thread is abandoned (it re-checks `stop` before any further
        put), matching the push side's join(timeout=10)."""
        stop.set()
        deadline = time.time() + 10.0
        while t.is_alive() and time.time() < deadline:
            try:                     # unblock a producer stuck on put()
                out.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.05)
        if self._pull_thread is t:
            self._pull_thread = None
            self._cur_pull = None

    def cancel_pull(self):
        """Cancel the ACTIVE in-flight pull_ahead (if any) so a new one
        can start. Idempotent."""
        cur = self._cur_pull
        if cur is not None:
            self._cancel_generation(*cur)

    # -- push side -----------------------------------------------------------
    def push_async(self, ids, grads, lr):
        """Queue a gradient push and return immediately. `grads` may be
        a live jax array — the worker thread forces it, so device->host
        readback overlaps the caller's next dispatch. Raises any error
        from a PREVIOUS push (at-most-depth delayed, never silent)."""
        if self._push_err is not None:
            err, self._push_err = self._push_err, None
            raise err
        self._push_q.put((ids, grads, float(lr)))

    def _push_loop(self):
        while True:
            item = self._push_q.get()
            if item is _Stop:
                return
            ids, grads, lr = item
            try:
                g = np.asarray(grads)        # forces device readback
                self.client.push(self.table_id, ids, g, lr)
            except Exception as e:           # noqa: BLE001
                self._push_err = e
                try:
                    from ..fleet.utils import log_util
                    log_util.log_json(
                        'ps_push_failed', level='error',
                        logger_name='ps', table=self.table_id,
                        rows=int(getattr(ids, 'size', 0)), error=repr(e))
                except Exception:
                    pass
            finally:
                self._push_q.task_done()

    def flush(self):
        """Barrier: wait until every queued push has landed on the
        servers (reference sync-mode fence). Re-raises a push error."""
        self._push_q.join()
        if self._push_err is not None:
            err, self._push_err = self._push_err, None
            raise err

    def stop(self):
        """Graceful close: cancel any in-flight prefetch, fence queued
        pushes (re-raising a queued push error AFTER the threads are
        released, so an error can't leave the communicator wedged)."""
        self.cancel_pull()
        err = None
        try:
            self.flush()
        except Exception as e:       # noqa: BLE001 — re-raised below
            err = e
        self._push_q.put(_Stop)
        self._push_thread.join(timeout=10)
        if err is not None:
            raise err

    close = stop

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.stop()
        return False
