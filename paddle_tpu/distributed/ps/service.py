"""Parameter-server service: remote pull/push over TCP.

Reference parity: paddle/fluid/distributed/service — BrpcPsServer/
BrpcPsClient (sendrecv.proto dense/sparse push-pull) and the
PsService RPC surface (N30). The transport is a compact binary protocol over
TCP sockets; the table math stays in C++ (csrc/sparse_table.cc) on the
server. Workers shard feature ids across servers by the same hash the
tables use internally, so a multi-host deployment scales horizontally like
the reference's PS cluster.

Wire protocol (little-endian):
  u8 op ('P' pull, 'U' push, 'S' save, 'L' load, 'N' size, 'Q' shutdown)
  u32 table_id
  P: u32 n, i64[n] ids                  -> f32[n*dim] rows
  U: u32 n, f32 lr, i64[n] ids, f32[n*dim] grads -> u8 ok
  S/L: u32 len, path bytes              -> u8 ok
  N: -> i64 size
"""
import socket
import struct
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...core.native import NativeSparseTable


def _read_n(sock, n):
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class PsServer:
    """Parity: BrpcPsServer — hosts tables, serves pull/push."""

    def __init__(self, host='0.0.0.0', port=0):
        self.tables = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._threads = []

    def add_table(self, table_id, dim, optimizer='adagrad', init_range=0.05,
                  num_shards=16, seed=0):
        """Parity: table config from the_one_ps proto."""
        self.tables[table_id] = NativeSparseTable(
            dim, num_shards=num_shards, optimizer=optimizer,
            init_range=init_range, seed=seed)
        return self.tables[table_id]

    def start(self):
        self._running = True
        self._sock.listen(64)
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn):
        try:
            while True:
                op = _read_n(conn, 1)
                if op == b'Q':
                    conn.sendall(b'\x01')
                    self.stop()
                    return
                (tid,) = struct.unpack('<I', _read_n(conn, 4))
                table = self.tables[tid]
                if op == b'P':
                    (n,) = struct.unpack('<I', _read_n(conn, 4))
                    ids = np.frombuffer(_read_n(conn, 8 * n), np.int64)
                    rows = table.pull(ids)
                    conn.sendall(rows.tobytes())
                elif op == b'U':
                    n, lr = struct.unpack('<If', _read_n(conn, 8))
                    ids = np.frombuffer(_read_n(conn, 8 * n), np.int64)
                    grads = np.frombuffer(
                        _read_n(conn, 4 * n * table.dim),
                        np.float32).reshape(n, table.dim)
                    table.push(ids, grads, lr)
                    conn.sendall(b'\x01')
                elif op in (b'S', b'L'):
                    (ln,) = struct.unpack('<I', _read_n(conn, 4))
                    path = _read_n(conn, ln).decode()
                    (table.save if op == b'S' else table.load)(path)
                    conn.sendall(b'\x01')
                elif op == b'N':
                    conn.sendall(struct.pack('<q', len(table)))
                else:
                    return
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def run(self):
        """Blocking serve (parity: fleet.run_server)."""
        self.start()
        self._accept_thread.join()

    def stop(self):
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


class PsClient:
    """Parity: BrpcPsClient — shards requests across servers by id hash."""

    def __init__(self, endpoints, timeout=60):
        self._socks = []
        self._locks = []
        for ep in endpoints:
            host, port = ep.rsplit(':', 1)
            s = socket.create_connection((host, int(port)), timeout=timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(s)
            self._locks.append(threading.Lock())
        self.n_servers = len(self._socks)
        # shard requests fan out concurrently (reference BrpcPsClient issues
        # parallel RPCs; serial round-trips would scale latency with the
        # server count)
        self._pool = ThreadPoolExecutor(max_workers=min(self.n_servers, 16)) \
            if self.n_servers > 1 else None

    def _shard(self, ids):
        return (ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
                >> np.uint64(33)) % np.uint64(self.n_servers)

    def _fanout(self, fn, shard_ids):
        if self._pool is None or len(shard_ids) <= 1:
            for s in shard_ids:
                fn(s)
            return
        list(self._pool.map(fn, shard_ids))

    def pull(self, table_id, ids, dim):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((len(ids), dim), np.float32)
        shards = self._shard(ids)

        def one(s):
            mask = shards == s
            if not mask.any():
                return
            sub = ids[mask]
            with self._locks[s]:
                sock = self._socks[s]
                sock.sendall(b'P' + struct.pack('<II', table_id, len(sub))
                             + sub.tobytes())
                rows = np.frombuffer(_read_n(sock, 4 * len(sub) * dim),
                                     np.float32).reshape(len(sub), dim)
            out[mask] = rows
        self._fanout(one, range(self.n_servers))
        return out

    def push(self, table_id, ids, grads, lr):
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, np.float32)
        shards = self._shard(ids)

        def one(s):
            mask = shards == s
            if not mask.any():
                return
            sub = ids[mask]
            sub_g = grads[mask]
            with self._locks[s]:
                sock = self._socks[s]
                sock.sendall(b'U' + struct.pack('<IIf', table_id, len(sub),
                                                lr)
                             + sub.tobytes() + sub_g.tobytes())
                _read_n(sock, 1)
        self._fanout(one, range(self.n_servers))

    def save(self, table_id, path):
        for s in range(self.n_servers):
            with self._locks[s]:
                sock = self._socks[s]
                p = f"{path}.part{s}".encode()
                sock.sendall(b'S' + struct.pack('<II', table_id, len(p)) + p)
                _read_n(sock, 1)

    def table_size(self, table_id):
        total = 0
        for s in range(self.n_servers):
            with self._locks[s]:
                sock = self._socks[s]
                sock.sendall(b'N' + struct.pack('<I', table_id))
                (n,) = struct.unpack('<q', _read_n(sock, 8))
            total += n
        return total

    def shutdown(self):
        for s in range(self.n_servers):
            try:
                with self._locks[s]:
                    self._socks[s].sendall(b'Q')
                    _read_n(self._socks[s], 1)
            except (ConnectionError, OSError):
                pass

    def close(self):
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
