"""Parameter-server service: remote pull/push over TCP.

Reference parity: paddle/fluid/distributed/service — BrpcPsServer/
BrpcPsClient (sendrecv.proto dense/sparse push-pull) and the
PsService RPC surface (N30). The transport is a compact binary protocol over
TCP sockets; the table math stays in C++ (csrc/sparse_table.cc) on the
server. Workers shard feature ids across servers by the same hash the
tables use internally, so a multi-host deployment scales horizontally like
the reference's PS cluster.

Wire protocol (little-endian):
  u8 op ('P' pull, 'U' push, 'S' save, 'L' load, 'N' size, 'Q' shutdown,
         'H' heartbeat, 'd' dense pull, 'e' dense push, 'I' dense set)
  u32 table_id ('H' has none)
  P: u32 n, u32 dim, i64[n] ids         -> u8 ok, f32[n*dim] rows
  U: 16s client_uuid, u64 seq, u32 n, u32 dim, f32 lr, i64[n] ids,
     f32[n*dim] grads                   -> u8 ok
  S/L: u32 len, path bytes              -> u8 ok
  N: -> u8 ok, i64 size
  d: -> u8 ok, u32 size, f32[size]
  e: 16s client_uuid, u64 seq, f32 lr, u32 size, f32[size] grads -> u8 ok
  I: u32 size, f32[size] values         -> u8 ok
  H: -> u8 ok

Every response leads with a status byte: 0x01 ok, 0x00 application error
followed by u32 len + utf-8 message. Application errors (bad path, missing
table, wrong table kind) surface to the caller as PsError — they are NOT
transport failures and are not retried.

Pushes are NOT idempotent, so they carry a (client_uuid, seq) tag: a
retry after a lost ack replays the same tag and the server skips the
re-apply (at-most-once for the replayed request) while still acking.

Fault tolerance (parity: brpc keepalive + the Communicator's retry):
PsClient remembers endpoints and transparently reconnects with retry on
any transport error — a killed-and-relaunched server (reloading its table
snapshot) resumes serving the same workers; an optional heartbeat thread
tracks per-server liveness.
"""
import os
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...core.native import (NativeSparseTable, NativeDenseTable,
                            NativeSsdSparseTable)


def _read_n(sock, n):
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class PsError(RuntimeError):
    """Server-side application error (bad path, missing table, dim
    mismatch) — surfaced to the caller, never retried."""


def _read_status(sock):
    if _read_n(sock, 1) == b'\x01':
        return
    (ln,) = struct.unpack('<I', _read_n(sock, 4))
    raise PsError(_read_n(sock, ln).decode())


class PsServer:
    """Parity: BrpcPsServer — hosts tables, serves pull/push.

    `state_dir`: when set, the push replay-dedup high-water mark
    (client uuid → last applied seq) persists to
    `<state_dir>/applied.log` and is recovered (compacted) on
    construction — so at-most-once holds ACROSS server restart, not just
    within one process (VERDICT r3 #7: an un-acked push applied before a
    crash must not re-apply when the reconnecting client replays it).

    Durability ordering: marks buffer in memory and hit disk only at
    `checkpoint()` — AFTER the table data they refer to is flushed. A
    recovered mark therefore never dedups a replay whose data was lost
    (the silent-gradient-drop hazard); the converse window — crash
    between the table flush and the mark flush inside one checkpoint —
    re-applies that window's pushes (at-least-once there, documented)."""

    _APPLIED_REC = struct.Struct('<16sQ')

    def __init__(self, host='0.0.0.0', port=0, state_dir=None):
        self.tables = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._running = False
        self._threads = []
        self._conns = []
        self._conns_lock = threading.Lock()
        self._applied = {}          # client uuid -> last applied push seq
        self._applied_log = None
        self._applied_lock = threading.Lock()
        self._applied_pending = []
        self._die_after_apply = 0   # test hook: crash before ack
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            path = os.path.join(state_dir, 'applied.log')
            self._recover_applied(path, compact=True)
            self._applied_log = open(path, 'ab')

    def _recover_applied(self, path, compact=False):
        rec = self._APPLIED_REC
        try:
            with open(path, 'rb') as f:
                data = f.read()
        except FileNotFoundError:
            return
        n = len(data) // rec.size       # crash-truncated tail dropped
        for i in range(n):
            uuid, seq = rec.unpack_from(data, i * rec.size)
            self._applied[uuid] = seq
        if compact and n > len(self._applied):
            # the log is append-only; rewrite it as last-record-per-uuid
            # so it stays O(live clients), not O(pushes ever)
            tmp = path + '.tmp'
            with open(tmp, 'wb') as f:
                for u, q in self._applied.items():
                    f.write(rec.pack(u, q))
            os.replace(tmp, path)

    def _mark_applied(self, uuid, seq):
        self._applied[uuid] = seq
        if self._applied_log is not None:
            with self._applied_lock:
                self._applied_pending.append(
                    self._APPLIED_REC.pack(uuid, seq))

    def flush_applied(self):
        """Make buffered dedup marks durable. Call ONLY after the table
        data they refer to is durable — see checkpoint()."""
        if self._applied_log is None:
            return
        with self._applied_lock:
            pending, self._applied_pending = self._applied_pending, []
        if pending:
            self._applied_log.write(b''.join(pending))
            self._applied_log.flush()

    def checkpoint(self):
        """Durable point: flush table data first, then the marks that
        refer to it (see the ordering note in the class docstring)."""
        for t in self.tables.values():
            if hasattr(t, 'flush'):
                t.flush()
        self.flush_applied()

    def add_table(self, table_id, dim, optimizer='adagrad', init_range=0.05,
                  num_shards=16, seed=0, beta1=0.9, beta2=0.999, eps=1e-8,
                  ssd_path=None, mem_budget_rows=1 << 20, shard_num=None):
        """Parity: table config from the_one_ps proto (TableParameter:
        embedx dim, shard_num, per-table optimizer hypers, SSD spill)."""
        if shard_num is not None:     # ps.proto spelling
            num_shards = shard_num
        if ssd_path:
            self.tables[table_id] = NativeSsdSparseTable(
                dim, ssd_path, num_shards=num_shards, optimizer=optimizer,
                init_range=init_range, seed=seed, beta1=beta1, beta2=beta2,
                eps=eps, mem_budget_rows=mem_budget_rows)
        else:
            self.tables[table_id] = NativeSparseTable(
                dim, num_shards=num_shards, optimizer=optimizer,
                init_range=init_range, seed=seed, beta1=beta1, beta2=beta2,
                eps=eps)
        return self.tables[table_id]

    def add_dense_table(self, table_id, size, optimizer='sgd'):
        """Parity: CommonDenseTable config."""
        self.tables[table_id] = NativeDenseTable(size, optimizer=optimizer)
        return self.tables[table_id]

    def start(self):
        self._running = True
        self._sock.listen(64)
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept(self):
        while self._running:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _table(self, tid, dense=None):
        t = self.tables.get(tid)
        if t is None:
            raise KeyError(f"no table {tid} on this server")
        is_dense = isinstance(t, NativeDenseTable)
        if dense is not None and dense != is_dense:
            raise TypeError(f"table {tid} is "
                            f"{'dense' if is_dense else 'sparse'}")
        return t

    def _serve(self, conn):
        def ok(payload=b''):
            conn.sendall(b'\x01' + payload)

        def fail(e):
            msg = f"{type(e).__name__}: {e}".encode()[:65535]
            conn.sendall(b'\x00' + struct.pack('<I', len(msg)) + msg)

        try:
            while True:
                op = _read_n(conn, 1)
                if op == b'Q':
                    ok()
                    self.stop()
                    return
                if op == b'H':
                    ok()
                    continue
                (tid,) = struct.unpack('<I', _read_n(conn, 4))
                try:
                    # each branch reads its FULL request before any table
                    # lookup/apply, so application errors never desync
                    # the stream
                    if op == b'd':
                        rows = self._table(tid, dense=True).pull()
                        ok(struct.pack('<I', len(rows)) + rows.tobytes())
                    elif op == b'e':
                        uuid = _read_n(conn, 16)
                        (seq,) = struct.unpack('<Q', _read_n(conn, 8))
                        lr, n = struct.unpack('<fI', _read_n(conn, 8))
                        g = np.frombuffer(_read_n(conn, 4 * n), np.float32)
                        table = self._table(tid, dense=True)
                        if self._applied.get(uuid) != seq:  # replay dedup
                            table.push(g, lr)
                            self._mark_applied(uuid, seq)
                        if self._die_after_apply > 0:   # test hook:
                            self._die_after_apply -= 1  # crash pre-ack
                            self._crash()
                            return
                        ok()
                    elif op == b'I':
                        (n,) = struct.unpack('<I', _read_n(conn, 4))
                        vals = np.frombuffer(_read_n(conn, 4 * n),
                                             np.float32)
                        self._table(tid, dense=True).set(vals)
                        ok()
                    elif op == b'P':
                        n, dim = struct.unpack('<II', _read_n(conn, 8))
                        ids = np.frombuffer(_read_n(conn, 8 * n), np.int64)
                        table = self._table(tid, dense=False)
                        if table.dim != dim:
                            raise ValueError(
                                f"table {tid} dim {table.dim} != {dim}")
                        ok(table.pull(ids).tobytes())
                    elif op == b'U':
                        uuid = _read_n(conn, 16)
                        (seq,) = struct.unpack('<Q', _read_n(conn, 8))
                        n, dim, lr = struct.unpack('<IIf',
                                                   _read_n(conn, 12))
                        ids = np.frombuffer(_read_n(conn, 8 * n), np.int64)
                        grads = np.frombuffer(
                            _read_n(conn, 4 * n * dim),
                            np.float32).reshape(n, dim)
                        table = self._table(tid, dense=False)
                        if table.dim != dim:
                            raise ValueError(
                                f"table {tid} dim {table.dim} != {dim}")
                        if self._applied.get(uuid) != seq:  # replay dedup
                            table.push(ids, grads, lr)
                            self._mark_applied(uuid, seq)
                        if self._die_after_apply > 0:   # test hook:
                            self._die_after_apply -= 1  # crash pre-ack
                            self._crash()
                            return
                        ok()
                    elif op in (b'S', b'L'):
                        (ln,) = struct.unpack('<I', _read_n(conn, 4))
                        path = _read_n(conn, ln).decode()
                        table = self._table(tid)
                        if op == b'S':
                            table.save(path)
                            # data is durable now — advance the mark log
                            # and snapshot the high-water map beside the
                            # table so a restore resumes at-most-once
                            self.flush_applied()
                            rec = self._APPLIED_REC
                            with open(path + '.applied', 'wb') as f:
                                for u, q in list(self._applied.items()):
                                    f.write(rec.pack(u, q))
                        else:
                            table.load(path)
                            self._recover_applied(path + '.applied')
                        ok()
                    elif op == b'N':
                        ok(struct.pack('<q', len(self._table(tid))))
                    elif op == b'K':
                        (thr,) = struct.unpack('<f', _read_n(conn, 4))
                        n = self._table(tid, dense=False).shrink(thr)
                        ok(struct.pack('<q', int(n)))
                    else:
                        return
                except ConnectionError:
                    raise
                except Exception as e:   # application error, not transport
                    fail(e)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()
            with self._conns_lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    def run(self):
        """Blocking serve (parity: fleet.run_server)."""
        self.start()
        self._accept_thread.join()

    def _crash(self):
        """Test hook: die WITHOUT acking the in-flight push, modeling the
        dangerous window — push applied and made durable by a checkpoint,
        client never saw the ack and will replay against the restarted
        server."""
        self.checkpoint()
        self.stop()

    def stop(self):
        self._running = False
        try:   # wake the blocked accept so the kernel listener dies too
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:         # drop live worker connections too —
            try:                # a stop IS an outage, not a drain
                c.shutdown(socket.SHUT_RDWR)   # wakes the blocked recv
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class PsClient:
    """Parity: BrpcPsClient — shards requests across servers by id hash.

    Transport errors trigger transparent reconnect-with-retry (up to
    `retry_timeout` seconds), so a relaunched server resumes serving the
    same client; `start_heartbeat` tracks per-server liveness."""

    def __init__(self, endpoints, timeout=60, retry_timeout=30):
        self.endpoints = list(endpoints)
        self._timeout = timeout
        self._retry_timeout = retry_timeout
        self._socks = [None] * len(self.endpoints)
        self._locks = [threading.Lock() for _ in self.endpoints]
        self.n_servers = len(self.endpoints)
        self.alive = [True] * self.n_servers
        import uuid as _uuid
        self._uuid = _uuid.uuid4().bytes    # push replay-dedup identity
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._hb_thread = None
        self._hb_stop = threading.Event()
        for s in range(self.n_servers):
            self._connect(s)
        # shard requests fan out concurrently (reference BrpcPsClient issues
        # parallel RPCs; serial round-trips would scale latency with the
        # server count)
        self._pool = ThreadPoolExecutor(max_workers=min(self.n_servers, 16)) \
            if self.n_servers > 1 else None

    def _connect(self, s):
        host, port = self.endpoints[s].rsplit(':', 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._socks[s] = sock
        return sock

    def _rpc(self, s, fn):
        """Run `fn(sock)` under the server lock, reconnecting with retry
        on transport errors (caller must make fn a full request —
        replayable on a fresh connection)."""
        deadline = time.monotonic() + self._retry_timeout
        with self._locks[s]:
            while True:
                try:
                    if self._socks[s] is None:
                        self._connect(s)
                    out = fn(self._socks[s])
                    self.alive[s] = True
                    return out
                except (ConnectionError, OSError):
                    try:
                        if self._socks[s] is not None:
                            self._socks[s].close()
                    except OSError:
                        pass
                    self._socks[s] = None
                    self.alive[s] = False
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.2)

    # -- heartbeat (parity: brpc keepalive / Communicator heartbeats) -----
    def start_heartbeat(self, interval=1.0):
        if self._hb_thread is not None:
            return

        def loop():
            while not self._hb_stop.wait(interval):
                for s in range(self.n_servers):
                    used = None
                    try:
                        with self._locks[s]:
                            need_connect = self._socks[s] is None
                        if need_connect:
                            # connect OUTSIDE the lock: a blackholed host
                            # would otherwise stall every rpc behind the
                            # heartbeat's connect timeout
                            host, port = self.endpoints[s].rsplit(':', 1)
                            fresh = socket.create_connection(
                                (host, int(port)), timeout=self._timeout)
                            fresh.setsockopt(socket.IPPROTO_TCP,
                                             socket.TCP_NODELAY, 1)
                            with self._locks[s]:
                                if self._socks[s] is None:
                                    self._socks[s] = fresh
                                else:   # an _rpc beat us to it
                                    fresh.close()
                        with self._locks[s]:
                            used = self._socks[s]
                            if used is None:
                                continue
                            used.sendall(b'H')
                            _read_n(used, 1)
                        self.alive[s] = True
                    except (ConnectionError, OSError):
                        self.alive[s] = False
                        with self._locks[s]:
                            # only tear down the socket WE failed on; a
                            # concurrent _rpc may have reconnected already
                            if used is not None \
                                    and self._socks[s] is used:
                                try:
                                    used.close()
                                except OSError:
                                    pass
                                self._socks[s] = None
        self._hb_thread = threading.Thread(target=loop, daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self):
        if self._hb_thread is not None:
            self._hb_stop.set()
            self._hb_thread.join()
            self._hb_thread = None
            self._hb_stop.clear()

    def _shard(self, ids):
        return (ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
                >> np.uint64(33)) % np.uint64(self.n_servers)

    def _fanout(self, fn, shard_ids):
        if self._pool is None or len(shard_ids) <= 1:
            for s in shard_ids:
                fn(s)
            return
        list(self._pool.map(fn, shard_ids))

    def pull(self, table_id, ids, dim):
        from ...core.monitor import stat_add
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        stat_add('STAT_ps_client_pull_ids', len(ids))
        out = np.empty((len(ids), dim), np.float32)
        shards = self._shard(ids)

        def one(s):
            mask = shards == s
            if not mask.any():
                return
            sub = ids[mask]

            def req(sock):
                sock.sendall(b'P' + struct.pack('<III', table_id,
                                                len(sub), dim)
                             + sub.tobytes())
                _read_status(sock)
                return np.frombuffer(_read_n(sock, 4 * len(sub) * dim),
                                     np.float32).reshape(len(sub), dim)
            out[mask] = self._rpc(s, req)
        self._fanout(one, range(self.n_servers))
        return out

    def push(self, table_id, ids, grads, lr):
        from ...core.monitor import stat_add
        ids = np.ascontiguousarray(ids, np.int64).reshape(-1)
        stat_add('STAT_ps_client_push_ids', len(ids))
        grads = np.ascontiguousarray(grads, np.float32)
        shards = self._shard(ids)

        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        tag = self._uuid + struct.pack('<Q', seq)

        def one(s):
            mask = shards == s
            if not mask.any():
                return
            sub = ids[mask]
            sub_g = grads[mask]

            def req(sock):
                sock.sendall(b'U' + struct.pack('<I', table_id) + tag
                             + struct.pack('<IIf', len(sub),
                                           grads.shape[1], lr)
                             + sub.tobytes() + sub_g.tobytes())
                _read_status(sock)
            self._rpc(s, req)
        self._fanout(one, range(self.n_servers))

    def save(self, table_id, path):
        for s in range(self.n_servers):
            p = f"{path}.part{s}".encode()

            def req(sock, _p=p):
                sock.sendall(b'S' + struct.pack('<II', table_id, len(_p))
                             + _p)
                _read_status(sock)
            self._rpc(s, req)

    def table_size(self, table_id):
        total = 0
        for s in range(self.n_servers):
            def req(sock):
                sock.sendall(b'N' + struct.pack('<I', table_id))
                _read_status(sock)
                return struct.unpack('<q', _read_n(sock, 8))[0]
            total += self._rpc(s, req)
        return total

    def shrink(self, table_id, threshold):
        """Drop rows with L2 norm below threshold on every server
        (reference: fleet.shrink → SSDSparseTable/CommonSparseTable
        shrink for stale features). Returns total rows dropped."""
        total = 0
        for s in range(self.n_servers):
            def req(sock):
                sock.sendall(b'K' + struct.pack('<If', table_id,
                                                float(threshold)))
                _read_status(sock)
                return struct.unpack('<q', _read_n(sock, 8))[0]
            total += self._rpc(s, req)
        return total

    # -- dense table (one table lives on server table_id % n_servers) -----
    def _dense_server(self, table_id):
        return table_id % self.n_servers

    def dense_init(self, table_id, values):
        vals = np.ascontiguousarray(values, np.float32).reshape(-1)

        def req(sock):
            sock.sendall(b'I' + struct.pack('<II', table_id, len(vals))
                         + vals.tobytes())
            _read_status(sock)
        self._rpc(self._dense_server(table_id), req)

    def dense_pull(self, table_id):
        def req(sock):
            sock.sendall(b'd' + struct.pack('<I', table_id))
            _read_status(sock)
            (n,) = struct.unpack('<I', _read_n(sock, 4))
            return np.frombuffer(_read_n(sock, 4 * n), np.float32)
        return self._rpc(self._dense_server(table_id), req)

    def dense_save(self, table_id, path):
        """Dense tables live on ONE server (table_id % n_servers), so
        their save targets only that server (sparse save fans out to all
        shard servers)."""
        p = f"{path}.part{self._dense_server(table_id)}".encode()

        def req(sock):
            sock.sendall(b'S' + struct.pack('<II', table_id, len(p)) + p)
            _read_status(sock)
        self._rpc(self._dense_server(table_id), req)

    def dense_push(self, table_id, grad, lr):
        g = np.ascontiguousarray(grad, np.float32).reshape(-1)
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        tag = self._uuid + struct.pack('<Q', seq)

        def req(sock):
            sock.sendall(b'e' + struct.pack('<I', table_id) + tag
                         + struct.pack('<fI', lr, len(g)) + g.tobytes())
            _read_status(sock)
        self._rpc(self._dense_server(table_id), req)

    def shutdown(self):
        self.stop_heartbeat()
        for s in range(self.n_servers):
            try:
                with self._locks[s]:
                    if self._socks[s] is not None:
                        self._socks[s].sendall(b'Q')
                        _read_n(self._socks[s], 1)
            except (ConnectionError, OSError):
                pass

    def close(self):
        self.stop_heartbeat()
        for s in self._socks:
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass
