"""Host-side parameter server (reference: paddle/fluid/distributed — N30)."""
