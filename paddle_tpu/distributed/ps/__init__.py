"""Host-side parameter server (reference: paddle/fluid/distributed — N30)."""
from .communicator import AsyncCommunicator  # noqa: F401
