"""Distributed sparse embedding — the host-side parameter server coupling.

Reference parity: operators/pscore distributed_lookup_table + push_sparse
bridging the graph to the PS (N32), over CommonSparseTable (N30) /
heterPS (N31). TPU-native split (the heterPS analogue from SURVEY.md §7
step 9): the trillion-parameter sparse table lives in HOST memory
(csrc/sparse_table.cc); each step pulls the batch's rows into one
contiguous buffer (one H2D transfer), the TPU runs the dense math, and the
embedding gradients flow back through the autograd tape into an async push.
"""
import threading
import queue as _queue

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.autograd import record, grad_enabled
from ...core.native import NativeSparseTable
from ...nn.layer.base import Layer


class AsyncCommunicator:
    """Parity: distributed C++ Communicator:197 — background send queue for
    async sparse-grad push (a_sync mode)."""

    def __init__(self, send_queue_size=16):
        self._q = _queue.Queue(maxsize=send_queue_size)
        self._running = False
        self._thread = None

    def start(self):
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            table, ids, grads, lr = item
            table.push(ids, grads, lr)
            self._q.task_done()

    def send(self, table, ids, grads, lr):
        if not self._running:
            table.push(ids, grads, lr)
            return
        self._q.put((table, ids, grads, lr))

    def flush(self):
        if self._running:
            self._q.join()

    def stop(self):
        if self._running:
            self._q.put(None)
            self._running = False
            if self._thread is not None:
                self._thread.join()
                self._thread = None


_global_communicator = AsyncCommunicator()


def global_communicator():
    return _global_communicator


class GeoCommunicator:
    """Geo-SGD async mode (parity: SparseGeoTable +
    service/communicator.h GeoCommunicator): the worker trains a LOCAL
    native mirror at full speed; every k steps the accumulated WEIGHT
    DELTAS (not gradients) push to the server, which sums deltas from all
    workers, and fresh rows pull back into the mirror. The server table
    must use the 'sgd' accessor (delta applied via lr=-1 — the reference's
    geo SUM-table semantics).
    """

    def __init__(self, remote_table, dim, k_steps=10):
        self.remote = remote_table
        self.dim = dim
        self.k = max(1, int(k_steps))
        self.local = NativeSparseTable(dim, optimizer='sgd')
        self.base = {}          # id -> row at last sync
        self.touched = set()
        self._step = 0

    def _ensure(self, flat):
        """Materialize server rows for any not-yet-mirrored ids
        (O(batch): membership tests against the base dict)."""
        unseen = sorted({int(i) for i in flat} - self.base.keys())
        if unseen:
            unseen = np.asarray(unseen, np.int64)
            rows = self.remote.pull(unseen)
            self.local.set(unseen, rows)
            for j, i in enumerate(unseen):
                self.base[int(i)] = rows[j].copy()

    def pull(self, ids):
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        self._ensure(flat)
        return self.local.pull(flat)

    def push(self, ids, grads, lr):
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        self._ensure(flat)   # push without prior pull still needs a base
        self.local.push(flat, grads, lr)
        self.touched.update(int(i) for i in flat)
        self._step += 1
        if self._step % self.k == 0:
            self.sync()

    def sync(self):
        if not self.touched:
            return
        ids = np.array(sorted(self.touched), np.int64)
        delta = self.local.pull(ids) - np.stack(
            [self.base[int(i)] for i in ids])
        self.remote.push(ids, delta, -1.0)   # server: w += delta
        fresh = self.remote.pull(ids)
        self.local.set(ids, fresh)
        for j, i in enumerate(ids):
            self.base[int(i)] = fresh[j].copy()
        self.touched.clear()

    def save(self, path):
        self.sync()
        self.remote.save(path)

    def load(self, path):
        """Reload the base table and invalidate the mirror (rows re-pull
        lazily on next touch)."""
        self.remote.load(path)
        self.local = NativeSparseTable(self.dim, optimizer='sgd')
        self.base.clear()
        self.touched.clear()
        self._step = 0

    def __len__(self):
        return len(self.remote)


class _RemoteTable:
    """PsClient adapter with the NativeSparseTable surface."""

    def __init__(self, client, table_id, dim):
        self.client = client
        self.table_id = table_id
        self.dim = dim

    def pull(self, ids):
        return self.client.pull(self.table_id, ids, self.dim)

    def push(self, ids, grads, lr):
        self.client.push(self.table_id, ids, grads, lr)

    def save(self, path):
        self.client.save(self.table_id, path)

    def load(self, path):
        raise NotImplementedError("load via the server side")

    def __len__(self):
        return self.client.table_size(self.table_id)


class DistributedEmbedding(Layer):
    """Sparse embedding backed by the host PS table.

    Forward pulls rows for the batch ids; backward captures the row grads on
    the tape and routes them into push (sync, or async via the
    communicator). The table is unbounded — features materialize on first
    touch (reference accessor semantics)."""

    def __init__(self, embedding_dim, optimizer='adagrad', learning_rate=0.01,
                 init_range=0.05, num_shards=16, seed=0, a_sync=False,
                 endpoints=None, table_id=0, mode=None, geo_k=10, name=None):
        super().__init__()
        self.embedding_dim = embedding_dim
        if mode is None:
            mode = 'async' if a_sync else 'sync'
        if mode not in ('sync', 'async', 'geo'):
            raise ValueError(f"bad PS mode {mode!r}")
        if endpoints:
            # remote PS mode (parity: distributed_lookup_table →
            # BrpcPsClient): pull/push go to the server fleet
            from .service import PsClient
            self.table = _RemoteTable(PsClient(endpoints), table_id,
                                      embedding_dim)
        else:
            # geo deltas apply to the base table via sgd/lr=-1; the
            # accessor there must be 'sgd' (server-side: configure the
            # server's table with optimizer='sgd' for geo workers)
            self.table = NativeSparseTable(
                embedding_dim, num_shards=num_shards,
                optimizer='sgd' if mode == 'geo' else optimizer,
                init_range=init_range, seed=seed)
        if mode == 'geo':
            self.table = GeoCommunicator(self.table, embedding_dim,
                                         k_steps=geo_k)
        self.learning_rate = learning_rate
        self.mode = mode
        self.a_sync = mode == 'async'
        if self.a_sync:
            _global_communicator.start()

    def forward(self, ids):
        """ids: int Tensor [...]; returns [..., dim] float Tensor."""
        ids_np = np.asarray(ids.data).astype(np.int64)
        flat = ids_np.reshape(-1)
        rows = self.table.pull(flat)
        out_arr = jnp.asarray(rows).reshape(ids_np.shape +
                                            (self.embedding_dim,))
        out = Tensor(out_arr, stop_gradient=not grad_enabled())
        if not out.stop_gradient:
            table, lr, dim = self.table, self.learning_rate, \
                self.embedding_dim
            a_sync = self.a_sync

            def vjp_fn(ct):
                # host-side push — the PS path is eager by design (ids and
                # table live on the host); ct is concrete here
                g = np.asarray(ct, np.float32).reshape(-1, dim)
                if a_sync:
                    _global_communicator.send(table, flat, g, lr)
                else:
                    table.push(flat, g, lr)
                return []
            record('distributed_lookup_table', vjp_fn, [], [], [out])
        return out

    def flush(self):
        _global_communicator.flush()

    def save(self, path):
        self.table.save(path)

    def load(self, path):
        self.table.load(path)

    def __len__(self):
        return len(self.table)
