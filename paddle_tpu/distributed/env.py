"""Distributed environment.

Reference parity: python/paddle/distributed/parallel.py ParallelEnv (env-var
driven: PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS)
+ platform/gen_comm_id_helper (N8 bootstrap).

TPU-native model: ONE process per host drives all local chips through PJRT
(multi-controller across hosts via jax.distributed). "rank" therefore has two
levels, as on real TPU pods:
  * process rank  — jax.process_index() (host granularity, DCN)
  * device rank   — a position in the global device mesh (chip granularity,
    ICI); collectives inside pjit/shard_map address mesh axes, not ranks.
The paddle-style integer rank maps to the device rank so existing fleet
topology math (CommunicateTopology) carries over unchanged.
"""
import os

import jax


def _int_env(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv."""

    def __init__(self):
        self._device_id = _int_env('FLAGS_selected_tpus',
                                   _int_env('FLAGS_selected_gpus', 0))

    @property
    def rank(self):
        return _int_env('PADDLE_TRAINER_ID', 0)

    @property
    def world_size(self):
        n = _int_env('PADDLE_TRAINERS_NUM', 0)
        if n:
            return n
        return jax.device_count()

    @property
    def local_rank(self):
        return self.rank

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return os.environ.get('PADDLE_CURRENT_ENDPOINT', '127.0.0.1:6170')

    @property
    def trainer_endpoints(self):
        eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
        return eps.split(',') if eps else [self.current_endpoint]

    @property
    def nranks(self):
        return self.world_size


_parallel_env = None


def parallel_env():
    global _parallel_env
    if _parallel_env is None:
        _parallel_env = ParallelEnv()
    return _parallel_env


def get_rank(group=None):
    if group is not None and getattr(group, 'rank', None) is not None:
        return group.rank
    return parallel_env().rank


def get_world_size(group=None):
    if group is not None and getattr(group, 'nranks', None):
        return group.nranks
    return parallel_env().world_size


def is_initialized():
    from . import collective
    return collective._default_group is not None
