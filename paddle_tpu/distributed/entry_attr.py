"""Sparse-table entry policies (reference:
python/paddle/distributed/entry_attr.py:20-150) — admission rules for
paddle.static.nn.sparse_embedding rows under the parameter server:
ProbabilityEntry admits a new feature id with probability p,
CountFilterEntry admits once an id has been seen `count` times. The PS
runtime consumes `_to_attr()` strings in its table configs
(ps/ps_runtime.py TableParameter analogue)."""


class EntryAttr:
    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError("EntryAttr is base class")

    def __repr__(self):
        return self._to_attr()


class ProbabilityEntry(EntryAttr):
    def __init__(self, probability):
        super().__init__()
        if not isinstance(probability, float):
            raise ValueError("probability must be a float in (0, 1]")
        if probability <= 0 or probability > 1:
            raise ValueError("probability must be in (0, 1]")
        self._name = 'probability_entry'
        self._probability = probability

    def _to_attr(self):
        return ':'.join([self._name, str(self._probability)])


class CountFilterEntry(EntryAttr):
    def __init__(self, count_filter):
        super().__init__()
        if not isinstance(count_filter, int):
            raise ValueError("count_filter must be a non-negative int")
        if count_filter < 0:
            raise ValueError("count_filter must be a non-negative int")
        self._name = 'count_filter_entry'
        self._count_filter = count_filter

    def _to_attr(self):
        return ':'.join([self._name, str(self._count_filter)])
