from . import the_one_ps
