"""PS runtime front-end.

Reference parity: fleet/runtime/the_one_ps.py:434 TheOnePSRuntime — builds
the C++ parameter server from strategy protos. The TPU rebuild's PS lives in
paddle_tpu/distributed/ps (host-side embedding tables + dense TPU towers);
this runtime wires fleet.init_server/init_worker to it.
"""


class TheOnePSRuntime:
    def __init__(self):
        self._server = None
        self._worker = None

    def init_worker(self, fleet_obj):
        from ...ps.ps_runtime import get_or_create_worker
        self._worker = get_or_create_worker(fleet_obj)

    def init_server(self, fleet_obj, *args, **kwargs):
        from ...ps.ps_runtime import get_or_create_server
        self._server = get_or_create_server(fleet_obj)

    def run_server(self, fleet_obj):
        if self._server is None:
            self.init_server(fleet_obj)
        self._server.run()

    def stop_worker(self, fleet_obj):
        if self._worker is not None:
            self._worker.stop()


_runtime = None


def runtime():
    global _runtime
    if _runtime is None:
        _runtime = TheOnePSRuntime()
    return _runtime


def table_configs():
    """Resolved TableParameter dicts for the active PS deployment
    (strategy-programmed via set_table_configs, else PADDLE_PS_TABLES)."""
    from ...ps.ps_runtime import _table_configs
    return _table_configs()
