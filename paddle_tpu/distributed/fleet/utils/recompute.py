"""Activation recompute (gradient checkpointing) + tuned remat policies.

Reference parity: fleet/utils/recompute.py RecomputeFunction(PyLayer):63 —
drop activations in forward, re-forward inside backward with saved RNG
state. TPU-native: `jax.checkpoint` (remat) IS this transform, applied at
trace level so XLA rematerializes inside the fused backward; the eager tape
path uses the PyLayer re-forward for parity semantics.

Policy layer (ISSUE 12, docs/performance.md#remat-policy): models tag
contraction outputs with `checkpoint_name` (`tag_tensor` below) and the
engines wrap their traced loss/block functions in `apply_policy`, so the
save/recompute split is TUNED instead of all-or-nothing (TPP
arXiv:2104.05755: contractions are worth saving, elementwise chains are
cheap to recompute). Named policies:

  * 'none'                — no remat; XLA keeps every residual live;
  * 'full'                — `jax.checkpoint` with the default policy:
                            save nothing, recompute everything in the
                            backward (the pre-ISSUE-12 use_remat=True);
  * 'attn_mlp_boundaries' — save ONLY the tagged contraction outputs
                            (qkv/attention-context/out-proj, fc1/fc2,
                            the attn/MLP boundary set); layernorm, GELU,
                            dropout joins, softmax internals and the
                            embedding gather recompute in the backward;
  * 'dots'                — `jax.checkpoint_policies.dots_saveable`
                            (save every matmul output, tagged or not —
                            the stashing-1F1B engine default).

Resolution order (resolve_policy): explicit engine kwarg → the
`PTPU_REMAT_POLICY` env var → fleet strategy
`recompute_configs['policy']` (when `strategy.recompute` is enabled) →
the engine's own default. Remat is a pure scheduling transform: loss and
gradients are BIT-identical with any policy (tests/test_remat.py pins
this for all three engines).
"""
import os

import jax

from ....core import rng as rng_mod
from ....core.tensor import Tensor
from ....core.autograd import no_grad, grad_enabled
from ....autograd import PyLayer


class RecomputeFunction(PyLayer):
    """Parity: recompute.py:63."""

    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng_state = preserve_rng_state
        if preserve_rng_state:
            ctx.fw_rng_state = rng_mod.get_rng_state()
        ctx.inputs = []
        ctx.tensor_indices = []
        tensor_inputs = []
        for i, arg in enumerate(args):
            if isinstance(arg, Tensor):
                tensor_inputs.append(arg)
                ctx.tensor_indices.append(i)
                ctx.inputs.append(None)
            else:
                ctx.inputs.append(arg)
        ctx.save_for_backward(*tensor_inputs)
        with no_grad():
            outputs = run_function(*args)
        return outputs

    @staticmethod
    def backward(ctx, *grads):
        from ....core import autograd as ag
        tensors = ctx.saved_tensor()
        inputs = list(ctx.inputs)
        detached = []
        for idx, t in zip(ctx.tensor_indices, tensors):
            d = Tensor(t.data, stop_gradient=t.stop_gradient)
            inputs[idx] = d
            detached.append(d)

        saved_rng = None
        if ctx.preserve_rng_state:
            saved_rng = rng_mod.get_rng_state()
            rng_mod.set_rng_state(ctx.fw_rng_state)
        try:
            # PyLayer.apply calls backward under no_grad; the re-forward
            # must build a tape, and parameter grads must accumulate into
            # .grad (accumulate_leaves) — the whole point of recompute.
            with ag.enable_grad():
                outputs = ctx.run_function(*inputs)
        finally:
            if saved_rng is not None:
                rng_mod.set_rng_state(saved_rng)

        outs = outputs if isinstance(outputs, (tuple, list)) else [outputs]
        outs = [o for o in outs if isinstance(o, Tensor)]
        gts = list(grads)[:len(outs)]
        cap = {id(d): None for d in detached if not d.stop_gradient}
        ag.backward(list(outs), gts, retain_graph=False, capture=cap,
                    accumulate_leaves=True)
        return tuple(Tensor(cap[id(d)]) if cap.get(id(d)) is not None
                     else None for d in detached)


def recompute(function, *args, **kwargs):
    """Parity: paddle.distributed.fleet.utils.recompute."""
    preserve = kwargs.pop('preserve_rng_state', True)
    use_reentrant = kwargs.pop('use_reentrant', True)
    if not grad_enabled():
        return function(*args, **kwargs)
    return _recompute_eager(function, preserve, *args)


def _recompute_eager(function, preserve, *args):
    from ....core import autograd as ag

    ctx = {}
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    needs = [not t.stop_gradient for t in tensor_args]
    fw_rng = rng_mod.get_rng_state() if preserve else None
    with no_grad():
        outputs = function(*args)
    multi = isinstance(outputs, (tuple, list))
    outs = list(outputs) if multi else [outputs]

    if not any(needs):
        return outputs

    def vjp_fn(cts):
        cts_list = list(cts) if isinstance(cts, tuple) else [cts]
        detached = []
        new_args = []
        for a in args:
            if isinstance(a, Tensor):
                d = Tensor(a.data, stop_gradient=a.stop_gradient)
                detached.append(d)
                new_args.append(d)
            else:
                new_args.append(a)
        saved = rng_mod.get_rng_state()
        if fw_rng is not None:
            rng_mod.set_rng_state(fw_rng)
        try:
            with ag.enable_grad():
                re_out = function(*new_args)
        finally:
            rng_mod.set_rng_state(saved)
        re_outs = list(re_out) if isinstance(re_out, (tuple, list)) \
            else [re_out]
        cap = {id(d): None for d in detached if not d.stop_gradient}
        ag.backward(re_outs, [Tensor(c) for c in cts_list], capture=cap,
                    accumulate_leaves=True)
        result = []
        for d in detached:
            g = cap.get(id(d))
            result.append(g)
        return result

    detached_outs = [Tensor(o.data, stop_gradient=False) for o in outs]
    ag.record('recompute', vjp_fn, tensor_args, needs, detached_outs)
    return tuple(detached_outs) if multi else detached_outs[0]


def recompute_jax(function):
    """The trace-level transform: jax.checkpoint / remat for jitted steps —
    the preferred TPU path (XLA rematerializes inside the fused backward)."""
    return jax.checkpoint(function)


# ---------------------------------------------------------------------------
# remat policy layer (ISSUE 12)
# ---------------------------------------------------------------------------

# checkpoint_name tags the models emit at contraction boundaries. The
# attn_mlp_boundaries policy saves exactly these; anything else is
# recomputed in the backward (TPP: cheap elementwise loops re-fuse).
BOUNDARY_NAMES = ('attn_qkv', 'attn_ctx', 'attn_out',
                  'mlp_fc1', 'mlp_out', 'embed_out')

POLICY_NAMES = ('none', 'full', 'attn_mlp_boundaries', 'dots')


def checkpoint_policy(name):
    """(remat_on, jax_policy_or_None) for a named policy."""
    if name in (None, 'none', False):
        return False, None
    if name in ('full', True):
        return True, None
    if name == 'attn_mlp_boundaries':
        return True, jax.checkpoint_policies.save_only_these_names(
            *BOUNDARY_NAMES)
    if name == 'dots':
        pol = getattr(jax.checkpoint_policies, 'dots_saveable', None) \
            or jax.checkpoint_policies.checkpoint_dots
        return True, pol
    raise ValueError(
        f"unknown remat policy {name!r}; expected one of {POLICY_NAMES}")


def resolve_policy(policy=None, default='none'):
    """Resolve the remat policy: engine kwarg -> PTPU_REMAT_POLICY env ->
    fleet strategy recompute_configs['policy'] (when strategy.recompute
    is on) -> `default`. Returns the policy NAME (validated) — or None
    when `default` is None and nothing was specified anywhere (the
    engine keeps its own legacy behavior, e.g. the stashing 1F1B's
    save-dots split)."""
    if policy is None:
        v = os.environ.get('PTPU_REMAT_POLICY')
        if v:
            policy = v
    if policy is None:
        try:
            from .. import fleet as _fleet_mod
            strategy = _fleet_mod._user_defined_strategy
            if strategy is not None and strategy.recompute:
                policy = (strategy.recompute_configs or {}).get('policy')
        except Exception:
            policy = None
    if policy is None:
        policy = default
    if policy is None:
        return None
    if policy is True:
        policy = 'full'
    if policy is False:
        policy = 'none'
    checkpoint_policy(policy)   # validate early, not at first dispatch
    return policy


def apply_policy(fn, policy, engine=None):
    """Wrap a traced function in `jax.checkpoint` per the named policy
    ('none' returns fn unchanged) and publish the decision gauge."""
    name = policy if isinstance(policy, str) else (
        'full' if policy else 'none')
    on, jax_policy = checkpoint_policy(name)
    if engine is not None:
        _publish_policy(engine, name)
    if not on:
        return fn
    if jax_policy is None:
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=jax_policy)


def tag(x, name):
    """`checkpoint_name` on a raw array (trace-time identity; counted so
    the bench can report how many boundaries a trace carries)."""
    from jax.ad_checkpoint import checkpoint_name
    _count_boundary(name)
    return checkpoint_name(x, name)


def tag_tensor(t, name):
    """`checkpoint_name` on a Tensor through the op tape (the transform
    is an identity with a trivial vjp, so the eager path is a no-op
    passthrough and the traced path carries the name)."""
    from ....core.autograd import run_op
    from jax.ad_checkpoint import checkpoint_name
    _count_boundary(name)
    return run_op('checkpoint_name',
                  lambda a: checkpoint_name(a, name), [t])


def _count_boundary(name):
    try:
        from ....core.monitor import counter
        counter('ptpu_remat_boundaries_total',
                help='checkpoint_name boundary tags applied (trace-time), '
                     'by tag name',
                labelnames=('name',)).inc(1, name=name)
    except Exception:
        pass


def _publish_policy(engine, policy):
    try:
        from ....core.monitor import gauge
        g = gauge('ptpu_remat_policy_info',
                  help='active remat policy per engine (value 1; the '
                       'policy rides in the label)',
                  labelnames=('engine', 'policy'))
        # zero the engine's OTHER policy series so a rebuilt engine
        # (e.g. an in-process policy sweep) never leaves a stale series
        # that snapshot() could misreport as active
        for other in POLICY_NAMES:
            if other != policy:
                g.set(0, engine=engine, policy=other)
        g.set(1, engine=engine, policy=policy)
    except Exception:
        pass


def boundary_counts():
    """{tag name: trace-time count} from the monitor counter."""
    try:
        from ....core import monitor as _m
        m = _m.metrics().get('ptpu_remat_boundaries_total')
        if m is None:
            return {}
        return {labels[0] if labels else '': int(child.value())
                for labels, child in m._series().items()}
    except Exception:
        return {}


def snapshot():
    """StepTelemetry.snapshot()['remat'] payload: active policies per
    engine + the boundary-tag counts (None when nothing recorded)."""
    try:
        from ....core import monitor as _m
        reg = _m.metrics()
        policies = {}
        g = reg.get('ptpu_remat_policy_info')
        if g is not None:
            for labels, child in g._series().items():
                if child.value():
                    policies[labels[0]] = labels[1]
        bounds = boundary_counts()
        if not policies and not bounds:
            return None
        return {'policies': policies, 'boundaries': bounds,
                'boundary_total': int(sum(bounds.values()))}
    except Exception:
        return None
