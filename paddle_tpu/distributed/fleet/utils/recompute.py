"""Activation recompute (gradient checkpointing).

Reference parity: fleet/utils/recompute.py RecomputeFunction(PyLayer):63 —
drop activations in forward, re-forward inside backward with saved RNG
state. TPU-native: `jax.checkpoint` (remat) IS this transform, applied at
trace level so XLA rematerializes inside the fused backward; the eager tape
path uses the PyLayer re-forward for parity semantics.
"""
import jax

from ....core import rng as rng_mod
from ....core.tensor import Tensor
from ....core.autograd import no_grad, grad_enabled
from ....autograd import PyLayer


class RecomputeFunction(PyLayer):
    """Parity: recompute.py:63."""

    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng_state = preserve_rng_state
        if preserve_rng_state:
            ctx.fw_rng_state = rng_mod.get_rng_state()
        ctx.inputs = []
        ctx.tensor_indices = []
        tensor_inputs = []
        for i, arg in enumerate(args):
            if isinstance(arg, Tensor):
                tensor_inputs.append(arg)
                ctx.tensor_indices.append(i)
                ctx.inputs.append(None)
            else:
                ctx.inputs.append(arg)
        ctx.save_for_backward(*tensor_inputs)
        with no_grad():
            outputs = run_function(*args)
        return outputs

    @staticmethod
    def backward(ctx, *grads):
        from ....core import autograd as ag
        tensors = ctx.saved_tensor()
        inputs = list(ctx.inputs)
        detached = []
        for idx, t in zip(ctx.tensor_indices, tensors):
            d = Tensor(t.data, stop_gradient=t.stop_gradient)
            inputs[idx] = d
            detached.append(d)

        saved_rng = None
        if ctx.preserve_rng_state:
            saved_rng = rng_mod.get_rng_state()
            rng_mod.set_rng_state(ctx.fw_rng_state)
        try:
            # PyLayer.apply calls backward under no_grad; the re-forward
            # must build a tape, and parameter grads must accumulate into
            # .grad (accumulate_leaves) — the whole point of recompute.
            with ag.enable_grad():
                outputs = ctx.run_function(*inputs)
        finally:
            if saved_rng is not None:
                rng_mod.set_rng_state(saved_rng)

        outs = outputs if isinstance(outputs, (tuple, list)) else [outputs]
        outs = [o for o in outs if isinstance(o, Tensor)]
        gts = list(grads)[:len(outs)]
        cap = {id(d): None for d in detached if not d.stop_gradient}
        ag.backward(list(outs), gts, retain_graph=False, capture=cap,
                    accumulate_leaves=True)
        return tuple(Tensor(cap[id(d)]) if cap.get(id(d)) is not None
                     else None for d in detached)


def recompute(function, *args, **kwargs):
    """Parity: paddle.distributed.fleet.utils.recompute."""
    preserve = kwargs.pop('preserve_rng_state', True)
    use_reentrant = kwargs.pop('use_reentrant', True)
    if not grad_enabled():
        return function(*args, **kwargs)
    return _recompute_eager(function, preserve, *args)


def _recompute_eager(function, preserve, *args):
    from ....core import autograd as ag

    ctx = {}
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    needs = [not t.stop_gradient for t in tensor_args]
    fw_rng = rng_mod.get_rng_state() if preserve else None
    with no_grad():
        outputs = function(*args)
    multi = isinstance(outputs, (tuple, list))
    outs = list(outputs) if multi else [outputs]

    if not any(needs):
        return outputs

    def vjp_fn(cts):
        cts_list = list(cts) if isinstance(cts, tuple) else [cts]
        detached = []
        new_args = []
        for a in args:
            if isinstance(a, Tensor):
                d = Tensor(a.data, stop_gradient=a.stop_gradient)
                detached.append(d)
                new_args.append(d)
            else:
                new_args.append(a)
        saved = rng_mod.get_rng_state()
        if fw_rng is not None:
            rng_mod.set_rng_state(fw_rng)
        try:
            with ag.enable_grad():
                re_out = function(*new_args)
        finally:
            rng_mod.set_rng_state(saved)
        re_outs = list(re_out) if isinstance(re_out, (tuple, list)) \
            else [re_out]
        cap = {id(d): None for d in detached if not d.stop_gradient}
        ag.backward(re_outs, [Tensor(c) for c in cts_list], capture=cap,
                    accumulate_leaves=True)
        result = []
        for d in detached:
            g = cap.get(id(d))
            result.append(g)
        return result

    detached_outs = [Tensor(o.data, stop_gradient=False) for o in outs]
    ag.record('recompute', vjp_fn, tensor_args, needs, detached_outs)
    return tuple(detached_outs) if multi else detached_outs[0]


def recompute_jax(function):
    """The trace-level transform: jax.checkpoint / remat for jitted steps —
    the preferred TPU path (XLA rematerializes inside the fused backward)."""
    return jax.checkpoint(function)
