"""Filesystem abstraction (parity: fleet/utils/fs.py — LocalFS:119,
HDFSClient:423). HDFS degrades to a clear error without a client binary."""
import os
import shutil


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError


class LocalFS(FS):
    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(path):
            if os.path.isdir(os.path.join(path, f)):
                dirs.append(f)
            else:
                files.append(f)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        open(path, 'a').close()

    def cat(self, path):
        with open(path) as f:
            return f.read()

    def list_dirs(self, path):
        dirs, _ = self.ls_dir(path)
        return dirs


class HDFSClient(FS):
    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._hadoop_home = hadoop_home
        if hadoop_home is None or not os.path.exists(str(hadoop_home)):
            self._available = False
        else:
            self._available = True

    def _need(self):
        if not self._available:
            raise RuntimeError("HDFS client binary unavailable in this "
                               "environment")

    def is_exist(self, path):
        self._need()

    def ls_dir(self, path):
        self._need()
