"""Communication-reduction training utilities (dygraph side).

Reference parity: the eager counterparts of the LocalSGD / GradientMerge
meta-optimizers (fleet/meta_optimizers/localsgd_optimizer.py:27 — @SNAPSHOT
params, k-step delta allreduce, A.11; gradient_merge_optimizer.py — k-step
grad accumulation with a conditional update).
"""
import numpy as np
import jax.numpy as jnp

from ....core.tensor import Tensor
from ... import collective as C


class LocalSGD:
    """Train locally k steps, then average params across the dp group.

    Parity: LocalSGDOptimizer (@SNAPSHOT + allreduce of deltas). On the
    single-controller SPMD runtime, param averaging is a pmean inside an
    SPMD region; eagerly (1 process) it is the identity, matching the
    reference's degenerate case.
    """

    def __init__(self, optimizer, k_steps=4, group=None):
        self._inner = optimizer
        self.k_steps = k_steps
        self.group = group
        self._step_i = 0
        self._snapshots = {}

    def _snapshot(self):
        for p in self._inner._parameter_list or []:
            self._snapshots[id(p)] = p.data

    def step(self):
        if not self._snapshots:
            self._snapshot()
        self._inner.step()
        self._step_i += 1
        if self._step_i % self.k_steps == 0:
            self._sync()

    def _sync(self):
        # Outside an SPMD region eager all_reduce is an identity, so the
        # delta must NOT be divided — only average when a real collective
        # ran (in-region the divisor is the group size).
        if C.in_spmd_region():
            for p in self._inner._parameter_list or []:
                delta = Tensor(p.data - self._snapshots[id(p)])
                C.all_reduce(delta, group=self.group)
                n = C.get_world_size(self.group)
                p.data = self._snapshots[id(p)] + delta.data / n
        self._snapshot()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        self._inner.clear_grad()

    def __getattr__(self, item):
        return getattr(self.__dict__['_inner'], item)


class AdaptiveLocalSGD(LocalSGD):
    """Parity: adaptive_localsgd — adjust k from loss progress."""

    def __init__(self, optimizer, init_k_steps=1, max_k_steps=16,
                 group=None):
        super().__init__(optimizer, init_k_steps, group)
        self.max_k_steps = max_k_steps
        self._last_loss = None

    def report_loss(self, loss):
        v = float(loss)
        if self._last_loss is not None and v < self._last_loss:
            self.k_steps = min(self.k_steps * 2, self.max_k_steps)
        else:
            self.k_steps = max(1, self.k_steps // 2)
        self._last_loss = v


class GradientMerge:
    """Accumulate grads k steps, then one optimizer update (parity:
    GradientMergeOptimizer:6255 — @GRAD@MERGED buffers + conditional
    block)."""

    def __init__(self, optimizer, k_steps=4, avg=True):
        self._inner = optimizer
        self.k_steps = k_steps
        self.avg = avg
        self._step_i = 0
        self._merged = {}

    def step(self):
        self._step_i += 1
        for p in self._inner._parameter_list or []:
            if p.grad is None:
                continue
            acc = self._merged.get(id(p))
            self._merged[id(p)] = p.grad.data if acc is None \
                else acc + p.grad.data
            p.grad = None
        if self._step_i % self.k_steps == 0:
            for p in self._inner._parameter_list or []:
                acc = self._merged.pop(id(p), None)
                if acc is None:
                    continue
                if self.avg:
                    acc = acc / self.k_steps
                p.grad = Tensor(acc)
            self._inner.step()
            self._inner.clear_grad()

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        pass  # grads are consumed into the merge buffers

    def __getattr__(self, item):
        return getattr(self.__dict__['_inner'], item)
