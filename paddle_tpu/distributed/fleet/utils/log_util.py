"""Fleet logger (parity: fleet/utils/log_util.py)."""
import logging
import os
import sys

logger = logging.getLogger('paddle_tpu.fleet')
if not logger.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        '%(asctime)s %(levelname)s [rank '
        + os.environ.get('PADDLE_TRAINER_ID', '0') + '] %(message)s'))
    logger.addHandler(h)
    logger.setLevel(os.environ.get('FLEET_LOG_LEVEL', 'INFO'))


def layer_to_str(base, *args, **kwargs):
    name = base + "("
    name += ", ".join(str(a) for a in args)
    if kwargs:
        name += ", " + ", ".join(f"{k}={v}" for k, v in kwargs.items())
    return name + ")"
