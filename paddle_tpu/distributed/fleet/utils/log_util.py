"""Fleet logger (parity: fleet/utils/log_util.py) — rank-aware
structured logging for the distributed stack.

The reference's log_util is a bare logging.Logger; production fleets
need machine-parseable, per-rank logs (the "when it breaks" layer):

  * every record carries rank / role / step fields — `set_role()` is
    stamped by launch/elastic/PS roles, `set_step()` by the train loop;
  * stderr keeps the human format (or JSON with FLEET_LOG_FORMAT=json);
  * with FLEET_LOG_DIR set, each rank ALSO appends JSON-lines to
    `<dir>/workerlog.<rank>.jsonl` — the file fleetrun tails and
    tools/health_dump.py cross-references with hang/OOM reports;
  * `log_json(event, **fields)` is the structured entry point the
    watchdog, OOM guard, elastic manager and PS communicator use; extra
    fields land in the record's `fields` dict, schema below.

JSON-line schema (one object per line):
  {"ts": epoch_seconds, "iso": iso8601, "level": "INFO", "logger": name,
   "rank": int, "role": str, "step": int|null, "event": str|null,
   "msg": str, "fields": {...}}   — `parse_line()` round-trips it.
"""
import datetime
import json
import logging
import os
import sys
import threading

__all__ = ['logger', 'get_logger', 'log_json', 'set_role', 'set_step',
           'parse_line', 'JsonLineFormatter', 'configure', 'layer_to_str']

_state = threading.local()
_role = os.environ.get('PADDLE_TRAINING_ROLE', 'trainer').lower()


def _rank():
    try:
        return int(os.environ.get('PADDLE_TRAINER_ID', '0') or 0)
    except ValueError:
        return 0


def set_role(role):
    """Process-wide role stamped on every record (trainer / launcher /
    pserver / elastic / watchdog ...)."""
    global _role
    _role = str(role)


def set_step(step):
    """Current train step (thread-local; engines stamp it per step)."""
    _state.step = step


def current_step():
    return getattr(_state, 'step', None)


class _ContextFilter(logging.Filter):
    """Attach rank/role/step to every record (also re-reads the rank
    env so a logger created before fleetrun's env injection heals)."""

    def filter(self, record):
        record.rank = _rank()
        record.role = _role
        record.step = current_step()
        if not hasattr(record, 'event'):
            record.event = None
        if not hasattr(record, 'fields'):
            record.fields = None
        return True


class JsonLineFormatter(logging.Formatter):
    def format(self, record):
        doc = {
            'ts': record.created,
            'iso': datetime.datetime.fromtimestamp(
                record.created).isoformat(timespec='milliseconds'),
            'level': record.levelname,
            'logger': record.name,
            'rank': getattr(record, 'rank', _rank()),
            'role': getattr(record, 'role', _role),
            'step': getattr(record, 'step', None),
            'event': getattr(record, 'event', None),
            'msg': record.getMessage(),
        }
        fields = getattr(record, 'fields', None)
        if fields:
            doc['fields'] = {k: _jsonable(v) for k, v in fields.items()}
        if record.exc_info and record.exc_info[0] is not None:
            doc['exc'] = self.formatException(record.exc_info)
        return json.dumps(doc)


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class _HumanFormatter(logging.Formatter):
    def format(self, record):
        base = (f"{self.formatTime(record, '%Y-%m-%d %H:%M:%S')} "
                f"{record.levelname} "
                f"[rank {getattr(record, 'rank', 0)}"
                f"/{getattr(record, 'role', '?')}"
                + (f" step {record.step}"
                   if getattr(record, 'step', None) is not None else '')
                + f"] {record.getMessage()}")
        fields = getattr(record, 'fields', None)
        if fields:
            base += ' ' + ' '.join(f'{k}={_jsonable(v)}'
                                   for k, v in fields.items())
        if record.exc_info and record.exc_info[0] is not None:
            base += '\n' + self.formatException(record.exc_info)
        return base


def parse_line(line):
    """Round-trip a JSON log line back into its dict (tests + tooling)."""
    doc = json.loads(line)
    if not isinstance(doc, dict) or 'msg' not in doc:
        raise ValueError(f"not a fleet log line: {line[:80]!r}")
    return doc


_UNSET = object()
_configured_dir = None
_explicit_dir = None


def configure(logger_obj=None, log_dir=_UNSET, level=None, force=False):
    """(Re)install handlers: stderr (human or JSON per FLEET_LOG_FORMAT)
    plus, when a log dir is set, a per-rank JSON-lines file
    `workerlog.<rank>.jsonl`. Idempotent unless `force` or the dir
    changed. An EXPLICITLY passed `log_dir` is sticky: the per-record
    healing path (get_logger/log_json re-reading FLEET_LOG_DIR) must not
    tear down a handler the caller installed deliberately (pass
    `log_dir=None` explicitly to clear it)."""
    global _configured_dir, _explicit_dir
    lg = logger_obj or logger
    if log_dir is not _UNSET:
        _explicit_dir = log_dir
    log_dir = _explicit_dir if _explicit_dir is not None else \
        os.environ.get('FLEET_LOG_DIR')
    if lg.handlers and not force and log_dir == _configured_dir:
        if level:
            lg.setLevel(level)
        return lg
    for h in list(lg.handlers):
        lg.removeHandler(h)
        try:
            h.close()
        except Exception:
            pass
    # context rides on the HANDLERS: logger-level filters only run on
    # the originating logger, so records from child loggers
    # (log_json(..., logger_name=...)) would bypass a logger filter and
    # lose rank/role/step
    ctx = _ContextFilter()
    stream = logging.StreamHandler(sys.stderr)
    if os.environ.get('FLEET_LOG_FORMAT', 'text').lower() == 'json':
        stream.setFormatter(JsonLineFormatter())
    else:
        stream.setFormatter(_HumanFormatter())
    stream.addFilter(ctx)
    lg.addHandler(stream)
    if log_dir:
        try:
            os.makedirs(log_dir, exist_ok=True)
            # non-trainer roles (launcher, pserver) get their own file:
            # the launcher shares FLEET_LOG_DIR with its trainers and has
            # no PADDLE_TRAINER_ID, so a bare rank-keyed name would
            # interleave it into the rank-0 trainer's log
            fname = f'workerlog.{_rank()}.jsonl' if _role == 'trainer' \
                else f'workerlog.{_role}.{_rank()}.jsonl'
            fh = logging.FileHandler(os.path.join(log_dir, fname))
            fh.setFormatter(JsonLineFormatter())
            fh.addFilter(ctx)
            lg.addHandler(fh)
        except OSError:
            pass
    lg.setLevel(level or os.environ.get('FLEET_LOG_LEVEL', 'INFO'))
    lg.propagate = False
    _configured_dir = log_dir
    return lg


logger = logging.getLogger('paddle_tpu.fleet')
configure(logger)


def get_logger(name=None, level=None):
    """A child of the fleet logger sharing its handlers/context (pass a
    dotted suffix, e.g. get_logger('elastic'))."""
    configure(logger, level=level)   # heal handlers if env changed
    if not name:
        return logger
    return logger.getChild(name)


_LEVELS = {'debug': logging.DEBUG, 'info': logging.INFO,
           'warning': logging.WARNING, 'error': logging.ERROR,
           'critical': logging.CRITICAL}


def log_json(event, level='info', logger_name=None, msg=None, **fields):
    """Structured log entry: `event` is the machine key, `fields` the
    payload; msg defaults to the event name."""
    lg = get_logger(logger_name)
    lg.log(_LEVELS.get(str(level).lower(), logging.INFO),
           msg if msg is not None else event,
           extra={'event': event, 'fields': fields or None})


def layer_to_str(base, *args, **kwargs):
    name = base + "("
    name += ", ".join(str(a) for a in args)
    if kwargs:
        name += ", " + ", ".join(f"{k}={v}" for k, v in kwargs.items())
    return name + ")"
