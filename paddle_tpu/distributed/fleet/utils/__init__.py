"""fleet.utils (parity: fleet/utils/__init__.py)."""
from .recompute import recompute, recompute_jax
from .hybrid_parallel_util import (fused_allreduce_gradients,
                                   sharding_reduce_gradients, unwrap_model)
from .fs import LocalFS, HDFSClient
from .comm_reduce import LocalSGD, AdaptiveLocalSGD, GradientMerge
from .log_util import logger
