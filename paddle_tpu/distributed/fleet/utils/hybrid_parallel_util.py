"""Hybrid-parallel helpers.

Reference parity: fleet/utils/hybrid_parallel_util.py —
broadcast_mp_parameters:103, broadcast_dp_parameters:110,
fused_allreduce_gradients:117, sharding_reduce_gradients:124,
broadcast_input_data. Single-controller TPU note: parameter broadcast across
ranks is implicit (one process materializes one copy of each logical
parameter; replication is a sharding annotation), so the broadcast_* calls
are cheap invariant-asserts here, kept for API and call-site parity.
"""
import numpy as np

from ....core.tensor import Tensor
from ... import collective as C
from ...parallel import DataParallel


def broadcast_input_data(hcg, *inputs, **kwargs):
    """Parity: broadcast of inputs over the mp group (TP ranks must see the
    same batch)."""
    group = hcg.get_model_parallel_group() if hcg else None
    if group is not None and C.in_spmd_region():
        out = []
        for v in inputs:
            if isinstance(v, Tensor):
                C.broadcast(v, src=0, group=group)
            out.append(v)
        return tuple(out)
    return inputs


def broadcast_mp_parameters(model, hcg):
    pass  # single-controller: mp shards are distinct params by construction


def broadcast_dp_parameters(model, hcg):
    pass  # replication handled by sharding annotations in the SPMD step


def broadcast_sharding_parameters(model, hcg):
    pass


def fused_allreduce_gradients(parameter_list, hcg):
    """Parity: fused_allreduce_gradients:117 — dp-group grad sync."""
    group = hcg.get_data_parallel_group() if hcg else None
    params = [p for p in parameter_list
              if not p.stop_gradient and p.grad is not None]
    if not params:
        return
    if not C.in_spmd_region():
        return  # single device: nothing to reduce
    import jax.numpy as jnp
    flat = jnp.concatenate([p.grad.data.reshape(-1) for p in params])
    t = Tensor(flat)
    C.all_reduce(t, group=group)
    n = C.get_world_size(group)
    flat = t.data / n
    off = 0
    for p in params:
        sz = p.grad.size
        p.grad.data = flat[off:off + sz].reshape(p.grad.data.shape)
        off += sz


def sharding_reduce_gradients(parameter_list, hcg):
    """Parity: sharding_reduce_gradients:124 — reduce(+scatter) grads to
    their owning sharding rank. SPMD: psum_scatter over 'sharding' axis."""
    group = hcg.get_sharding_parallel_group() if hcg else None
    if not C.in_spmd_region():
        return
    for p in parameter_list:
        if p.grad is not None and not p.stop_gradient:
            C.all_reduce(p.grad, group=group)


def unwrap_model(model):
    from ..meta_parallel.meta_parallel_base import MetaParallelBase
    while isinstance(model, (MetaParallelBase, DataParallel)):
        model = model._layers
    return model
