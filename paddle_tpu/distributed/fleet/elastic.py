"""Elastic training manager.

Reference parity: fleet/elastic.py ElasticManager:99 — etcd-backed
membership (host register :171, watch callbacks :192-218), scale-up/down
detection, local-proc relaunch via LauncherInterface. TPU rebuild: the
native TCPStore replaces etcd (no external dependency); membership is
heartbeat keys with staleness-based death detection; the PJRT/jax.distributed
world restarts on membership change (XLA worlds are fixed-size — a resize is
a relaunch, same as the reference's re-exec path).
"""
import os
import threading
import time

from ...core.native import TCPStore  # noqa: F401  (re-exported for users)
from .utils import log_util


class LauncherInterface:
    """Parity: elastic.py LauncherInterface — local proc control."""

    def __init__(self, procs=None):
        self.procs = procs or []

    def _terminate_procs(self):
        import signal
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.2)
            if p.poll() is None:
                p.kill()

    def launch(self):
        raise NotImplementedError

    def stop(self):
        self._terminate_procs()


class ElasticStatus:
    COMPLETED = 'completed'
    ERROR = 'error'
    HOLD = 'hold'
    RESTART = 'restart'
    EXIT = 'exit'


class ElasticManager:
    """Parity: elastic.py ElasticManager:99."""

    def __init__(self, args=None, store=None, job_id=None,
                 np_min=1, np_max=None, heartbeat_interval=2.0,
                 dead_after=10.0):
        self.job_id = job_id or os.environ.get('PADDLE_ELASTIC_JOB_ID',
                                               'default_job')
        self.np_min = np_min
        self.np_max = np_max
        self.heartbeat_interval = heartbeat_interval
        self.dead_after = dead_after
        self.store = store
        self.host = os.environ.get('PADDLE_CURRENT_ENDPOINT',
                                   '127.0.0.1:6170')
        self._stop = threading.Event()
        self._hb_thread = None
        self.enabled = store is not None

    # -- membership (reference: _host_register / _match / _update_hosts) ----
    def register(self):
        if not self.enabled:
            return
        self.store.set(self._key(self.host), str(time.time()))
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           daemon=True)
        self._hb_thread.start()
        log_util.log_json('elastic_register', logger_name='elastic',
                          job_id=self.job_id, host=self.host,
                          np_min=self.np_min, np_max=self.np_max)

    def _key(self, host):
        return f"elastic/{self.job_id}/{host}"

    def _heartbeat_loop(self):
        while not self._stop.is_set():
            self.store.set(self._key(self.host), str(time.time()))
            self._stop.wait(self.heartbeat_interval)

    def hosts(self, known_hosts):
        """Live hosts among `known_hosts` by heartbeat freshness."""
        now = time.time()
        alive = []
        for h in known_hosts:
            v = self.store.get(self._key(h), wait=False)
            if v is None:
                continue
            try:
                ts = float(v.decode())
            except ValueError:
                continue
            if now - ts < self.dead_after:
                alive.append(h)
        return alive

    def watch(self, known_hosts):
        """One watch tick → ElasticStatus (reference: watch loop :192-218)."""
        if not self.enabled:
            return ElasticStatus.COMPLETED
        alive = self.hosts(known_hosts)
        if len(alive) == len(known_hosts):
            return ElasticStatus.HOLD
        dead = [h for h in known_hosts if h not in alive]
        status = ElasticStatus.ERROR if len(alive) < self.np_min \
            else ElasticStatus.RESTART  # scale event → relaunch world
        log_util.log_json('elastic_membership_change', level='warning',
                          logger_name='elastic', job_id=self.job_id,
                          alive=alive, dead=dead, status=status)
        return status

    def exit(self, completed=True):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
