"""fleet datasets (reference:
python/paddle/distributed/fleet/dataset/dataset.py InMemoryDataset:259 /
QueueDataset) — the MultiSlot file-feed path for PS/CTR training,
backed by the native C++ feed (csrc/data_feed.cc via
core/native.NativeDataFeed): QueueDataset streams batches straight
from the file channel; InMemoryDataset loads + globally shuffles in
RAM first (the reference's load_into_memory / global_shuffle pair).
"""
import numpy as np

from ...core.tensor import Tensor


class DatasetBase:
    def __init__(self):
        self._slots = []
        self._batch_size = 1
        self._thread_num = 1
        self._filelist = []
        self._feed = None

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name=None,
             fs_ugi=None, **kwargs):
        """Configure like the reference's dataset.init(**kwargs):
        `use_var` gives the slot layout (static data Variables — dtype
        decides the float/int64 slot kind, shape[-1] the width)."""
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        if use_var:
            self._slots = []
            for v in use_var:
                width = int(np.prod([d for d in v.shape if d and d > 0])
                            or 1)
                kind = 'int64' if 'int' in str(v.dtype) else 'float'
                self._slots.append((width, kind))
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _build(self):
        from ...core.native import NativeDataFeed
        self._feed = NativeDataFeed(self._slots, self._batch_size,
                                    num_threads=self._thread_num)
        self._feed.set_filelist(self._filelist)
        return self._feed

    def _as_tensors(self, f, i):
        import jax.numpy as jnp
        out = []
        fo = io_ = 0
        for w, kind in self._slots:
            if kind == 'float':
                out.append(Tensor(jnp.asarray(f[:, fo:fo + w])))
                fo += w
            else:
                out.append(Tensor(jnp.asarray(i[:, io_:io_ + w])))
                io_ += w
        return out


class QueueDataset(DatasetBase):
    """Streaming dataset: batches come off the multi-thread file
    channel in arrival order (reference QueueDataset)."""

    def __iter__(self):
        feed = self._build()
        feed.start()
        for f, i in feed:
            yield self._as_tensors(f, i)


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference InMemoryDataset:
    load_into_memory + local/global_shuffle + release_memory)."""

    def __init__(self):
        super().__init__()
        self._loaded = False
        self._seed = 0

    def load_into_memory(self):
        self._build()
        self._feed.load_into_memory(seed=self._seed)
        self._loaded = True

    def local_shuffle(self):
        self._shuffle(seed=self._seed + 1)

    def global_shuffle(self, fleet=None, thread_num=None):
        # one-process global == local; under fleetrun each rank holds
        # its file shard and shuffles it (the reference's semantics
        # reduce to this when the shard is per-rank disjoint)
        self._shuffle(seed=self._seed + 1)

    def _shuffle(self, seed):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        self._seed = seed
        self._feed.load_into_memory(seed=seed)

    def get_memory_data_size(self, fleet=None):
        if not self._loaded:
            return 0
        return int(self._feed.memory_size())

    def release_memory(self):
        self._feed = None
        self._loaded = False

    def __iter__(self):
        if not self._loaded:
            raise RuntimeError(
                "InMemoryDataset: call load_into_memory() before "
                "iterating (QueueDataset streams directly)")
        for f, i in self._feed.iter_memory():
            yield self._as_tensors(f, i)
