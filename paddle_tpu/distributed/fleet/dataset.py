"""fleet datasets (reference:
python/paddle/distributed/fleet/dataset/dataset.py InMemoryDataset:259 /
QueueDataset / FileInstantDataset:1112 / BoxPSDataset:1142) — the
MultiSlot file-feed path for PS/CTR training, backed by the native C++
feed (csrc/data_feed.cc via core/native.NativeDataFeed): QueueDataset
streams batches straight from the file channel; InMemoryDataset loads +
globally shuffles in RAM first (the reference's load_into_memory /
global_shuffle pair).

pipe_command is real: like the reference trainer, the dataset spawns
the command once per input file, streams the raw file through its
stdin, and parses count-prefixed MultiSlot text (the DataGenerator
wire protocol) off its stdout — bridged to the native feed's dense
fixed-width layout (`_multislot_to_dense`).
"""
import os
import subprocess
import tempfile

import numpy as np

from ...core.tensor import Tensor


class DatasetBase:
    def __init__(self):
        self._slots = []
        self._batch_size = 1
        self._thread_num = 1
        self._filelist = []
        self._feed = None
        self._pipe_command = None
        self._pipe_tmpdir = None

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name=None,
             fs_ugi=None, **kwargs):
        """Configure like the reference's dataset.init(**kwargs):
        `use_var` gives the slot layout (static data Variables — dtype
        decides the float/int64 slot kind, shape[-1] the width)."""
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        if pipe_command:
            self._pipe_command = pipe_command
        if use_var:
            self._slots = []
            for v in use_var:
                width = int(np.prod([d for d in v.shape if d and d > 0])
                            or 1)
                kind = 'int64' if 'int' in str(v.dtype) else 'float'
                self._slots.append((width, kind))
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_pipe_command(self, pipe_command):
        """Reference parity: each input file is streamed through this
        shell command (usually `python my_generator_script.py` running
        a DataGenerator subclass); the command's stdout must be
        count-prefixed MultiSlot text."""
        self._pipe_command = pipe_command

    def _multislot_to_dense(self, text_lines, out_path):
        """Bridge the DataGenerator wire protocol to the native feed's
        dense pipe-separated layout: '<n> v1..vn <m> u1..um' ->
        'v1..vn | u1..um'. TPU constraint: every slot's count must
        equal its declared fixed width (no LoD) — mismatch is a loud
        error, not a silent pad."""
        widths = [w for w, _ in self._slots]
        with open(out_path, 'w') as out:
            for ln, line in enumerate(text_lines, 1):
                toks = line.split()
                if not toks:
                    continue
                pos, fields = 0, []
                for si, w in enumerate(widths):
                    if pos >= len(toks):
                        raise ValueError(
                            f"pipe output line {ln}: expected "
                            f"{len(widths)} slots, ran out at {si}")
                    n = int(toks[pos])
                    if n != w:
                        raise ValueError(
                            f"pipe output line {ln} slot {si}: count "
                            f"{n} != declared fixed width {w} (the "
                            "TPU feed is dense/no-LoD; pad in "
                            "generate_sample)")
                    fields.append(' '.join(toks[pos + 1:pos + 1 + n]))
                    pos += 1 + n
                if pos != len(toks):
                    raise ValueError(
                        f"pipe output line {ln}: {len(toks) - pos} "
                        "trailing tokens after the declared slots")
                out.write(' | '.join(fields) + '\n')

    def _run_pipe(self):
        """Run pipe_command over each input file (the reference
        trainer's per-file pipe), writing native-format temp files.

        Streams the command's stdout line-by-line into the converter
        instead of buffering the whole shard in RAM (capture_output
        would hold stdout AND the decoded split simultaneously — a
        multi-GB CTR shard exhausts the host). stderr drains in a side
        thread (only the tail is kept) so a chatty generator can't
        deadlock the pipe; the returncode check happens after EOF."""
        import threading

        self._pipe_tmpdir = tempfile.TemporaryDirectory(
            prefix='paddle_tpu_pipe_')
        converted = []
        for i, path in enumerate(self._filelist):
            dst = os.path.join(self._pipe_tmpdir.name, f'part-{i}')
            with open(path, 'rb') as src:
                proc = subprocess.Popen(
                    self._pipe_command, shell=True, stdin=src,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE)
                stderr_tail = []

                def drain(stream=proc.stderr, tail=stderr_tail):
                    while True:
                        chunk = stream.read(65536)
                        if not chunk:
                            return
                        tail.append(chunk)
                        del tail[:-16]       # keep ~1MB of tail
                t = threading.Thread(target=drain, daemon=True)
                t.start()
                parse_err = None
                try:
                    lines = (ln.decode(errors='replace')
                             for ln in proc.stdout)
                    self._multislot_to_dense(lines, dst)
                except ValueError as e:
                    # a command that crashed mid-stream also produces
                    # garbage/truncated lines — report the rc + stderr
                    # (below), not the downstream parse symptom
                    parse_err = e
                finally:
                    proc.stdout.close()
                    rc = proc.wait()
                    t.join(timeout=10)
            if rc != 0:
                err = b''.join(stderr_tail).decode(errors='replace')
                raise RuntimeError(
                    f"pipe_command failed on {path} (rc={rc}): "
                    f"{err[-1000:]}") from parse_err
            if parse_err is not None:
                raise parse_err
            converted.append(dst)
        return converted

    def _build(self):
        from ...core.native import NativeDataFeed
        files = self._run_pipe() if self._pipe_command \
            else self._filelist
        self._feed = NativeDataFeed(self._slots, self._batch_size,
                                    num_threads=self._thread_num)
        self._feed.set_filelist(files)
        return self._feed

    def _as_tensors(self, f, i):
        import jax.numpy as jnp
        out = []
        fo = io_ = 0
        for w, kind in self._slots:
            if kind == 'float':
                out.append(Tensor(jnp.asarray(f[:, fo:fo + w])))
                fo += w
            else:
                out.append(Tensor(jnp.asarray(i[:, io_:io_ + w])))
                io_ += w
        return out


class QueueDataset(DatasetBase):
    """Streaming dataset: batches come off the multi-thread file
    channel in arrival order (reference QueueDataset)."""

    def __iter__(self):
        feed = self._build()
        feed.start()
        for f, i in feed:
            yield self._as_tensors(f, i)


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference InMemoryDataset:
    load_into_memory + local/global_shuffle + release_memory)."""

    def __init__(self):
        super().__init__()
        self._loaded = False
        self._seed = 0

    def load_into_memory(self):
        self._build()
        self._feed.load_into_memory(seed=self._seed)
        self._loaded = True

    def local_shuffle(self):
        self._shuffle(seed=self._seed + 1)

    def global_shuffle(self, fleet=None, thread_num=None):
        # one-process global == local; under fleetrun each rank holds
        # its file shard and shuffles it (the reference's semantics
        # reduce to this when the shard is per-rank disjoint)
        self._shuffle(seed=self._seed + 1)

    def _shuffle(self, seed):
        if not self._loaded:
            raise RuntimeError("call load_into_memory() first")
        self._seed = seed
        self._feed.load_into_memory(seed=seed)

    def get_memory_data_size(self, fleet=None):
        if not self._loaded:
            return 0
        return int(self._feed.memory_size())

    def release_memory(self):
        self._feed = None
        self._loaded = False

    def __iter__(self):
        if not self._loaded:
            raise RuntimeError(
                "InMemoryDataset: call load_into_memory() before "
                "iterating (QueueDataset streams directly)")
        for f, i in self._feed.iter_memory():
            yield self._as_tensors(f, i)


class FileInstantDataset(QueueDataset):
    """Single-pass instant file feed (reference FileInstantDataset:
    dataset.py:1112 over InstantDataFeed): batches stream in strict
    file order with no memory stage and no shuffle. The native channel
    already preserves arrival order at thread_num=1; init() pins that
    so ported scripts get the reference's deterministic pass."""

    def init(self, **kwargs):
        kwargs.setdefault('thread_num', 1)
        super().init(**kwargs)
        if self._thread_num != 1:
            self._thread_num = 1       # instant feed is one ordered pass
        return self


class BoxPSDataset(InMemoryDataset):
    """BoxPS dataset surface (reference BoxPSDataset: dataset.py:1142).
    The reference pairs it with the GPU BoxPS embedding cache;
    this build has no box cache to warm or flush — the PS embedding
    store is csrc/sparse_table (SSD-spill tier), which serves pulls
    directly — so the pass-boundary hooks are genuine no-ops here and
    preload maps onto the in-memory load path."""

    def begin_pass(self):
        return None

    def end_pass(self, need_save_delta=False):
        return None

    def preload_into_memory(self, file_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        return None

    def slots_shuffle(self, slots):
        # reference: shuffles chosen sparse slots' feasigns for feature
        # ablation; dense fixed-width rows have no per-slot feasign
        # lists to permute independently, so this stays a loud raiser
        raise NotImplementedError(
            "BoxPSDataset.slots_shuffle: per-slot feasign shuffling "
            "assumes LoD sparse slots; the TPU feed is dense "
            "fixed-width. Shuffle in generate_sample, or use "
            "local_shuffle() for whole-row permutation.")
