"""ShardingParallel wrapper (parity:
fleet/meta_parallel/sharding_parallel.py) — ZeRO grouping is done by
DygraphShardingOptimizer; this wrapper only broadcasts params at setup."""
from .meta_parallel_base import MetaParallelBase
from ..utils.hybrid_parallel_util import broadcast_sharding_parameters


class ShardingParallel(MetaParallelBase):
    def _prepare_for_model(self):
        broadcast_sharding_parameters(self._layers, self._hcg)
