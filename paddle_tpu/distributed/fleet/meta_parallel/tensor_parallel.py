"""TensorParallel wrapper (parity: fleet/meta_parallel/tensor_parallel.py) —
broadcasts inputs across the mp group and dp-syncs grads; the mp collectives
live inside the mp_layers."""
from .meta_parallel_base import MetaParallelBase
from ..utils.hybrid_parallel_util import (broadcast_input_data,
                                          broadcast_mp_parameters,
                                          broadcast_dp_parameters,
                                          fused_allreduce_gradients)


class TensorParallel(MetaParallelBase):
    def _prepare_for_model(self):
        broadcast_mp_parameters(self._layers, self._hcg)
        broadcast_dp_parameters(self._layers, self._hcg)

    def forward(self, *inputs, **kwargs):
        inputs = broadcast_input_data(self._hcg, *inputs)
        return self._layers(*inputs, **kwargs)
