"""Hybrid-parallel SPMD train step (DP × TP × ZeRO sharding).

Reference parity: the execution semantics of fleet's hybrid dygraph engines —
DataParallel grad allreduce (imperative/reducer.cc), TensorParallel
(mp_layers + mp ring collectives), DygraphShardingOptimizer ZeRO-1
(dygraph_sharding_optimizer.py:27) — composed per the topology's axis layout
(SURVEY.md A.1).

TPU-native design: ONE `jax.jit(shard_map(step))` over the registered Mesh.
  * batch sharded over ('dp','sharding') on axis 0 — ZeRO ranks ARE
    data-parallel ranks; params replicated over both;
  * TP params sharded over 'mp' at their `split_axis` (mp_layers emit the
    explicit collectives inside the traced forward);
  * ZeRO-1: optimizer states (incl. fp32 master weights) sharded over
    'sharding'; grads reduce-scattered, the local param shard updated, and
    params all-gathered — the reduce-scatter/all-gather placement matches
    the automatic cross-replica weight-update sharding technique
    (arXiv:2004.13336) and ShardingOptimizer's broadcast/reduce vocabulary;
  * dp grad sync is a single fused pmean per param (XLA coalesces —
    the FusedAllReduce equivalent).
All of forward, backward (jax.grad at trace level), collectives, and the
optimizer fuse into one XLA executable with donated buffers.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P, NamedSharding
from jax.experimental.shard_map import shard_map

from ....core import rng as rng_mod
from ....core import autograd
from ....core import async_step as A_
from ....core import bucketing as B
from ....core.tensor import Tensor
from ....jit import bind_arrays
from ... import collective as C
from ... import topology_runtime




def _param_spec(p, mesh_axes, zero_axis=None):
    """PartitionSpec for a parameter array."""
    ndim = len(p.data.shape)
    spec = [None] * ndim
    if getattr(p, 'is_distributed', False) and 'mp' in mesh_axes:
        spec[p.split_axis] = 'mp'
    return P(*spec)


from .meta_parallel_base import EngineTeardown


class HybridParallelTrainStep(A_.AsyncDispatchMixin, EngineTeardown):
    """Compile a full train step over the registered mesh.

    loss_fn(model, *batch) -> scalar loss Tensor. Batch tensors are sharded
    on axis 0 over ('dp','sharding') — leading batch dims must divide
    dp*sharding_degree; when the mesh has sp>1 (and the model declares
    _supports_sequence_parallel), every batch tensor of rank >= 2 is ALSO
    sharded on axis 1 over 'sp' — pass `sp_shard_args` (a set of positional
    batch indices) to restrict sequence sharding to the token-aligned
    tensors if the loss takes non-sequence rank-2 inputs.
    """

    def __init__(self, model, loss_fn, optimizer, mesh=None,
                 accumulate_steps=1, use_remat=False, sp_shard_args=None,
                 use_buckets=None, comm_dtype=None, bucket_mb=None,
                 comm_block=None, comm_overlap=None, prefetch_depth=None,
                 comm_chunk=None, remat_policy=None,
                 sequence_parallel=None, dispatch_window=None,
                 device_lr=None):
        self.sp_shard_args = sp_shard_args
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else topology_runtime.get_mesh()
        if self.mesh is None:
            raise ValueError("no mesh registered; fleet.init with "
                             "hybrid_configs first or build_mesh()")
        self.axes = tuple(self.mesh.axis_names)
        if 'pp' in self.axes and self.mesh.shape['pp'] > 1:
            raise ValueError("pp>1: use SpmdPipelineEngine")
        self.accumulate_steps = accumulate_steps
        # tuned remat (docs/performance.md#remat-policy): kwarg -> env ->
        # strategy; the legacy `use_remat` bool only sets the default
        from ..utils.recompute import resolve_policy as _resolve_remat
        self._remat_policy = _resolve_remat(
            remat_policy, default='full' if use_remat else 'none')
        self.use_remat = self._remat_policy != 'none'
        self.dp = self.mesh.shape.get('dp', 1)
        self.sharding_deg = self.mesh.shape.get('sharding', 1)
        self.mp = self.mesh.shape.get('mp', 1)
        self.sp = self.mesh.shape.get('sp', 1)
        # Megatron-style sequence-parallel activation sharding
        # (docs/performance.md#sequence-parallel-activations): the
        # LayerNorm/dropout/residual segments between mp regions run on
        # token slices scattered over the mp group — only meaningful
        # with a live mp axis and a model that declares support
        self._seq_parallel = bool(
            C.resolve_sequence_parallel(sequence_parallel)
            and 'mp' in self.axes and self.mp > 1
            and getattr(model, '_supports_sequence_parallel', False))
        # params the model consumes on the SCATTERED token stream
        # (LayerNorms, row-parallel biases): their per-rank grads cover
        # only the local token slice, so the step psums them over 'mp'
        # to restore the full-token gradient the replicated route gets
        self._seq_grad_names = frozenset(
            n for n, p in model.named_parameters()
            if getattr(p, 'sequence_parallel_grad', False)
        ) if self._seq_parallel else frozenset()

        named = [(n, p) for n, p in model.named_parameters()
                 if not p.stop_gradient]
        self._names = [n for n, _ in named]
        self._params_by_name = dict(named)
        self._param_specs = {n: _param_spec(p, self.axes)
                             for n, p in named}
        # ZeRO eligibility: shard optimizer state over 'sharding' on axis 0
        self._zero_ok = {}
        for n, p in named:
            shp = p.data.shape
            ok = (self.sharding_deg > 1 and len(shp) >= 1
                  and shp[0] % self.sharding_deg == 0
                  and not (getattr(p, 'is_distributed', False)
                           and p.split_axis == 0))
            self._zero_ok[n] = ok

        # -- bucketed rs/ag weight-update sharding (arXiv:2004.13336) ------
        # data-parallel replication axes: every rank along them holds the
        # same params and a different batch shard — grads mean-reduce over
        # them and the weight update can shard 1/n per rank.
        self._rs_axes = tuple(a for a in ('dp', 'sharding', 'sp')
                              if a in self.axes and self.mesh.shape[a] > 1)
        self._n_shards = int(np.prod([self.mesh.shape[a]
                                      for a in self._rs_axes] or [1]))
        self.comm_dtype, self._bucket_bytes = B.resolve_comm_config(
            comm_dtype, bucket_mb)
        self._comm_block = B.resolve_comm_block(comm_block)
        # comm/compute overlap (ISSUE 10): layer-grouped buckets +
        # eager reduce-scatter + deferred/prefetched param all-gather.
        # Grouping only engages when there is real comm to overlap
        # (n_shards > 1) so the dp=1 compiled program stays unchanged.
        overlap_req, self._prefetch_depth, self._comm_chunk = \
            B.resolve_overlap_config(comm_overlap, prefetch_depth,
                                     comm_chunk)
        # mp-sharded params are already distributed (their state shards
        # with them); they keep the per-param path
        bucketable = [n for n, p in named
                      if not (getattr(p, 'is_distributed', False)
                              and 'mp' in self.axes and self.mp > 1)]
        self._layout = None
        if bucketable and B.elementwise(optimizer):
            self._layout = B.BucketLayout.build(
                {n: (self._params_by_name[n].data.shape,
                     self._params_by_name[n].data.dtype)
                 for n in bucketable},
                bucket_bytes=self._bucket_bytes,
                pad_to=max(self._n_shards, 1) * 8,
                group_fn=(B.layer_group_fn
                          if overlap_req and self._n_shards > 1
                          else None))
        self._bucketed = bool(
            self._layout is not None and self._n_shards > 1
            and use_buckets is not False)
        self._overlap = bool(overlap_req and self._bucketed)
        if self._overlap:
            B.ensure_overlap_xla_flags()
        if self._layout is not None:
            B.publish_comm_gauges(self._layout, engine='hybrid',
                                  n_shards=max(self._n_shards, 1),
                                  comm_dtype=self.comm_dtype,
                                  enabled=self._bucketed,
                                  block=self._comm_block)
            B.publish_overlap_gauges(self._layout, engine='hybrid',
                                     n_shards=max(self._n_shards, 1),
                                     comm_dtype=self.comm_dtype,
                                     enabled=self._overlap,
                                     prefetch=self._prefetch_depth,
                                     chunk=self._comm_chunk,
                                     block=self._comm_block)
        if not self._bucketed:
            self._layout = None

        from ....core import memory as _mem
        with _mem.phase('engine.init'):
            # deferred gather: bucketed params live as flat 1/n SHARDS
            # between steps (ZeRO-3-style resident set); the full
            # replica only exists transiently inside the step, gathered
            # group-by-group just before first use
            slot_names = set(self._layout.slots) if self._overlap \
                else set()
            self._params = {n: self._place(p.data, self._param_specs[n])
                            for n, p in named if n not in slot_names}
            self._param_shards = []
            if self._overlap:
                shard_spec = P(self._rs_axes)
                for b in self._layout.buckets:
                    host = np.zeros((b.size,), b.dtype)
                    for s in b.slots:
                        host[s.offset:s.offset + s.size] = np.asarray(
                            jax.device_get(
                                self._params_by_name[s.name].data)
                        ).reshape(-1).astype(b.dtype)
                    self._param_shards.append(
                        self._place_flat(host, shard_spec))
            self._states = {'named': {}, 'buckets': []}
            self._state_specs = {'named': {}, 'buckets': []}
            legacy_names = set(self._names) if not self._bucketed else \
                set(self._names) - set(self._layout.slots)
            for n, p in named:
                if n not in legacy_names:
                    continue
                st = optimizer.init_state(p)
                if p.data.dtype != jnp.float32 and \
                        getattr(optimizer, '_multi_precision', True):
                    st['master'] = p.data.astype(jnp.float32)
                sspec = {}
                for k, v in st.items():
                    if self._zero_ok[n] and np.ndim(v) >= 1 \
                            and v.shape == p.data.shape:
                        # slice the state to this sharding rank
                        axes0 = list(self._param_specs[n])
                        axes0[0] = 'sharding'
                        sspec[k] = P(*axes0)
                    else:
                        sspec[k] = self._param_specs[n] if (
                            np.ndim(v) >= 1 and v.shape == p.data.shape) \
                            else P()
                    st[k] = self._place(v, sspec[k])
                self._states['named'][n] = st
                self._state_specs['named'][n] = sspec
            if self._bucketed:
                self._init_flat_states()

        self._grad_clip = optimizer._grad_clip
        self._compiled = None
        self._exec = None
        self._closed = False
        self._step_count = 0

        # -- async step pipeline (ISSUE 13,
        # docs/performance.md#async-dispatch): bounded in-flight dispatch
        # window + host-gap instrumentation + on-device LR schedule ------
        self._inflight = A_.DispatchWindow(
            A_.resolve_dispatch_window(dispatch_window))
        self._gap = A_.HostGapMonitor('hybrid')
        # step-time ledger (ISSUE 16): reconciled wall decomposition +
        # model-FLOPs accounting, published from flush()
        from ....core import ledger as _led
        self._ledger = _led.StepLedger(
            'hybrid', gap=self._gap,
            params_fn=lambda: _led.count_params(
                list(self._params_by_name.values())),
            remat_policy=self._remat_policy)
        # batch input specs are init-time facts (DeviceLoader asks for
        # them before the first dispatch)
        self._sp_on = ('sp' in self.axes and self.sp > 1
                       and getattr(model, '_supports_sequence_parallel',
                                   False))
        self._batch_axes = tuple(a for a in ('dp', 'sharding')
                                 if a in self.axes
                                 and self.mesh.shape[a] > 1)
        from ....optimizer import device_lr as _dlr
        self._lr = _dlr.LrFeed(optimizer, device_lr,
                               place=lambda a: self._place(a, P()))

    def _init_flat_states(self):
        """Sharded flat optimizer state, one entry per bucket: vector
        states (moments, fp32 master) are GLOBAL 1-D arrays of the
        bucket's padded length sharded over the dp axes — each rank
        materializes only its 1/n shard (ZeRO-1); scalars (beta powers)
        replicate. Built via make_array_from_callback so no device ever
        holds a full fp32 replica."""
        opt = self.optimizer
        shard_spec = P(self._rs_axes)
        for b in self._layout.buckets:
            flat32 = np.zeros((b.size,), np.float32)
            for s in b.slots:
                flat32[s.offset:s.offset + s.size] = np.asarray(
                    jax.device_get(self._params_by_name[s.name].data),
                    np.float32).reshape(-1)
            st = B.init_bucket_state(
                opt, b, flat32,
                force_master=B._is_int8(self.comm_dtype))
            placed, sspec = {}, {}
            for k, v in st.items():
                if np.ndim(v) >= 1:
                    placed[k] = self._place_flat(v, shard_spec)
                    sspec[k] = shard_spec
                else:
                    placed[k] = self._place(v, P())
                    sspec[k] = P()
            self._states['buckets'].append(placed)
            self._state_specs['buckets'].append(sspec)

    def _place_flat(self, host_arr, spec):
        host_arr = np.asarray(host_arr)
        sh = NamedSharding(self.mesh, spec)
        return jax.make_array_from_callback(
            host_arr.shape, sh, lambda idx: host_arr[idx])

    def _place(self, arr, spec):
        # copy before placing: device_put to a (partially) replicated
        # sharding can alias the source buffer, and the jitted step DONATES
        # these arrays — aliasing would free the model's eager params.
        return jax.device_put(jnp.array(arr, copy=True),
                              NamedSharding(self.mesh, spec))

    # -- the SPMD step --------------------------------------------------------
    def _build(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        axes = self.axes
        # numerics taps (core/numerics.py): latched at build — the taps
        # change the compiled step's output signature, so flip the flag
        # BEFORE the first dispatch (a later flip needs a new engine)
        from ....core import numerics as _num
        taps_on = self._taps_on = _num.taps_enabled()
        # axes whose shards see different data → loss/grad pmean + distinct
        # dropout keys ('sp' chunks are different tokens, like dp shards).
        # Must stay the SAME axis set the bucket reduce_scatter and the
        # P(_rs_axes) flat-state sharding use, or grads and params desync.
        dp_axes = self._rs_axes
        zero_ok = self._zero_ok
        s = self.sharding_deg
        from ..utils.recompute import apply_policy as _apply_remat
        remat_policy = self._remat_policy
        seq_parallel = self._seq_parallel

        def global_norm_sq(grads):
            """Mesh-wide global grad-norm^2: mp-sharded params psum
            their local sum of squares (shared by taps + clip)."""
            sq_d = jnp.asarray(0.0, jnp.float32)
            sq_r = jnp.asarray(0.0, jnp.float32)
            for n, g in grads.items():
                p = self._params_by_name[n]
                v = jnp.sum(g.astype(jnp.float32) ** 2)
                if getattr(p, 'is_distributed', False) and 'mp' in axes:
                    sq_d = sq_d + v
                else:
                    sq_r = sq_r + v
            if 'mp' in axes and self.mp > 1:
                sq_d = lax.psum(sq_d, 'mp')
            return sq_d + sq_r

        bucketed = self._bucketed
        layout = self._layout
        rs_axes = self._rs_axes
        n_shards = self._n_shards
        comm_dtype = self.comm_dtype
        comm_block = self._comm_block
        overlap = self._overlap
        prefetch_depth = self._prefetch_depth
        comm_chunk = self._comm_chunk

        def clip_factor(gn_sq_val):
            from ....nn.clip import ClipGradByGlobalNorm
            if self._grad_clip is None:
                return None
            if not (isinstance(self._grad_clip, ClipGradByGlobalNorm)
                    or hasattr(self._grad_clip, '_clip')):
                return None
            clip_norm = getattr(self._grad_clip, 'clip_norm',
                                None) or getattr(
                    getattr(self._grad_clip, '_clip', None),
                    'clip_norm', 1.0)
            gn = jnp.sqrt(gn_sq_val)
            return factor_from(gn, clip_norm)

        def factor_from(gn, clip_norm):
            return clip_norm / jnp.maximum(gn, clip_norm)

        def step(params, states, lr, key, *batch):
            with C.spmd_region(axes, sp_data_sharded=sp_on,
                               mp_seq_parallel=seq_parallel):
                # -- deferred/prefetched param all-gather (overlap
                # mode): bucketed params arrive as 1/n shards; rebuild
                # the working replica group-by-group IN LAYER ORDER at
                # the top of the step, where the latency-hiding
                # scheduler can run group g's gather under the forward
                # compute of groups < g. `prefetch_depth` bounds the
                # in-flight window: an optimization_barrier makes
                # gather g data-depend on gather g-depth, so at most
                # `depth` full groups are live beyond the shards.
                shards_in = None
                if overlap:
                    shards_in = params['shards']
                    gathered_p = B.gather_groups(
                        shards_in, rs_axes, n_shards,
                        comm_dtype=comm_dtype, block=comm_block,
                        chunk=comm_chunk, prefetch=prefetch_depth)
                    params = dict(params['named'])
                    params.update(layout.unflatten(gathered_p))

                def loss_of(ps):
                    with bind_arrays(model, ps):
                        # fold data-parallel position into the key so dp
                        # shards draw different dropout masks; mp ranks share
                        # the key (TP-consistent dropout — A.5; per-rank
                        # divergence goes through the RNGStatesTracker)
                        k = key
                        for a in dp_axes:
                            k = jax.random.fold_in(k, lax.axis_index(a))
                        with rng_mod.rng_guard(k), autograd.no_grad():
                            loss = loss_fn(model, *[Tensor(b)
                                                    for b in batch])
                    return loss.data.astype(jnp.float32)

                lf = _apply_remat(loss_of, remat_policy,
                                             engine='hybrid')
                loss, raw_grads = jax.value_and_grad(lf)(params)
                if seq_parallel and self._seq_grad_names:
                    # scattered-segment params: sum the per-token-slice
                    # grads over the mp group (full-token gradient)
                    raw_grads = {
                        n: (lax.psum(g, 'mp')
                            if n in self._seq_grad_names else g)
                        for n, g in raw_grads.items()}
                if dp_axes:
                    loss = lax.pmean(loss, dp_axes)

                named_states = states['named']
                if not bucketed:
                    grads = raw_grads
                    if dp_axes:
                        grads = {n: lax.pmean(g, dp_axes)
                                 for n, g in grads.items()}

                    # numerics taps: PRE-CLIP grads (the clip below rebinds
                    # `grads` to a new dict) + the mesh-wide global
                    # grad-norm^2 (same reduction the clip uses)
                    gn_sq = None
                    preclip_grads = grads
                    if taps_on:
                        gn_sq = global_norm_sq(grads)

                    # mesh-aware global-norm clip (parity:
                    # HybridParallelClipGrad,
                    # hybrid_parallel_optimizer.py:32)
                    factor = clip_factor(
                        gn_sq if gn_sq is not None
                        else global_norm_sq(grads)) \
                        if self._grad_clip is not None else None
                    if factor is not None:
                        grads = {n: (g.astype(jnp.float32) * factor)
                                 .astype(g.dtype)
                                 for n, g in grads.items()}

                    new_params, new_named = {}, {}
                    for n, p in params.items():
                        g = grads[n]
                        st = dict(named_states[n])
                        if zero_ok[n] and 'sharding' in axes and s > 1:
                            # ZeRO-1: reduce-scatter grad, update local
                            # shard, all-gather updated param.
                            rows = p.shape[0] // s
                            idx = lax.axis_index('sharding')
                            g_shard = lax.dynamic_slice_in_dim(
                                g, idx * rows, rows, axis=0)
                            p_shard = lax.dynamic_slice_in_dim(
                                p, idx * rows, rows, axis=0)
                            np_, ns = self._update_one(p_shard, g_shard,
                                                       st, lr)
                            p_new = lax.all_gather(np_, 'sharding', axis=0,
                                                   tiled=True)
                        else:
                            p_new, ns = self._update_one(p, g, st, lr)
                        new_params[n] = p_new
                        new_named[n] = ns
                    new_states = {'named': new_named, 'buckets': []}
                    if taps_on:
                        taps = _num.jit_taps(preclip_grads, new_params,
                                             extra_norm_sq=gn_sq)
                        return loss, new_params, new_states, taps
                    return loss, new_params, new_states

                # -- bucketed path (arXiv:2004.13336): flatten grads into
                # dtype-homogeneous buckets, ONE reduce_scatter per bucket
                # over the dp axes (compressed wire under comm_dtype),
                # sharded optimizer update on this rank's 1/n slice, ONE
                # all_gather per bucket for the updated params -----------
                legacy = {n: g for n, g in raw_grads.items()
                          if n not in layout.slots}
                if dp_axes:
                    legacy = {n: lax.pmean(g, dp_axes)
                              for n, g in legacy.items()}
                # layer-grouped buckets: each flat bucket depends only
                # on ITS layers' grads, so its reduce-scatter is
                # emitted as soon as those grads exist instead of
                # serializing behind the full backward; `chunk` splits
                # oversized buckets into schedulable pieces
                flat_grads = layout.flatten(
                    {n: raw_grads[n] for n in layout.slots})
                shards32 = [B.reduce_scatter(f, rs_axes, n_shards,
                                             comm_dtype=comm_dtype,
                                             mean=True,
                                             block=comm_block,
                                             chunk=comm_chunk)
                            for f in flat_grads]

                # taps diagnostics mode pays an extra pmean to surface
                # fully-reduced per-param grads (the bucketed hot path
                # never materializes them)
                gn_sq = None
                preclip_grads = None
                if taps_on:
                    preclip_grads = dict(legacy)
                    preclip_grads.update(
                        {n: (lax.pmean(raw_grads[n], dp_axes)
                             if dp_axes else raw_grads[n])
                         for n in layout.slots})
                    gn_sq = global_norm_sq(preclip_grads)

                factor = None
                if self._grad_clip is not None:
                    # global grad-norm^2 from the bucket shards: shards
                    # are disjoint over the dp axes, so one psum restores
                    # the full sum; legacy (mp-sharded) params add their
                    # psum('mp') contribution exactly as the per-param
                    # path does. Each shard's contribution is ONE fused
                    # stats pass (Pallas kernel on TPU — the first leg
                    # of the fused optimizer step).
                    sq_local = sum(B.grad_stats(g)[0] for g in shards32) \
                        if shards32 else jnp.asarray(0.0, jnp.float32)
                    sq_b = lax.psum(sq_local, rs_axes) if rs_axes \
                        else sq_local
                    sq_b = sq_b + (global_norm_sq(legacy) if legacy
                                   else jnp.asarray(0.0, jnp.float32))
                    factor = clip_factor(sq_b)
                if factor is not None:
                    legacy = {n: (g.astype(jnp.float32) * factor)
                              .astype(g.dtype)
                              for n, g in legacy.items()}

                flat_params = None if overlap else layout.flatten(params)
                new_params, new_named = {}, {}
                new_buckets = []
                new_shards, gathered = [], []
                for gi, (b, g32, st) in enumerate(
                        zip(layout.buckets, shards32,
                            states['buckets'])):
                    # overlap: this rank's param shard IS the engine
                    # state (same values take_shard would slice out of
                    # the gathered replica — fp32/bf16 wires gather
                    # exactly, and under int8 the forced master makes
                    # the update independent of the working copy)
                    p_shard = shards_in[gi] if overlap else \
                        B.take_shard(flat_params[gi], rs_axes, n_shards)
                    # the clip multiply rides into the one-pass fused
                    # update as `prefactor` instead of a separate
                    # bucket-sized elementwise op
                    np_, ns = B.shard_update(self.optimizer, p_shard,
                                             g32, st, lr,
                                             prefactor=factor)
                    if overlap:
                        # deferred gather: the updated shard goes back
                        # out as engine state; its all-gather moves to
                        # the NEXT step's forward, just before first use
                        new_shards.append(np_)
                    else:
                        gathered.append(B.all_gather(
                            np_, rs_axes, comm_dtype=comm_dtype,
                            block=comm_block, chunk=comm_chunk,
                            n_shards=n_shards))
                    new_buckets.append(ns)
                if not overlap:
                    new_params.update(layout.unflatten(gathered))
                for n, g in legacy.items():
                    p = params[n]
                    st = dict(named_states[n])
                    if zero_ok[n] and 'sharding' in axes and s > 1:
                        # mp-sharded params keep the per-param ZeRO-1
                        # slice over 'sharding' (their states were
                        # created with that spec)
                        rows = p.shape[0] // s
                        idx = lax.axis_index('sharding')
                        g_shard = lax.dynamic_slice_in_dim(
                            g, idx * rows, rows, axis=0)
                        p_shard = lax.dynamic_slice_in_dim(
                            p, idx * rows, rows, axis=0)
                        np_, ns = self._update_one(p_shard, g_shard,
                                                   st, lr)
                        np_ = lax.all_gather(np_, 'sharding', axis=0,
                                             tiled=True)
                    else:
                        np_, ns = self._update_one(p, g, st, lr)
                    new_params[n] = np_
                    new_named[n] = ns
                new_states = {'named': new_named, 'buckets': new_buckets}
                out_params = {'named': new_params,
                              'shards': new_shards} if overlap \
                    else new_params
                if taps_on:
                    tap_params = new_params
                    if overlap:
                        # diagnostics mode pays the gather the hot path
                        # deferred, so per-param stats see full params
                        tap_params = dict(new_params)
                        tap_params.update(layout.unflatten(
                            B.gather_groups(new_shards, rs_axes,
                                            n_shards,
                                            comm_dtype=comm_dtype,
                                            block=comm_block,
                                            chunk=comm_chunk)))
                    taps = _num.jit_taps(preclip_grads, tap_params,
                                         extra_norm_sq=gn_sq)
                    return loss, out_params, new_states, taps
                return loss, out_params, new_states

        # sequence sharding only for models that declare support (GPT sets
        # _supports_sequence_parallel; others would silently attend within
        # chunks) — the mesh may still carry an sp axis for other tensors.
        sp_on = self._sp_on
        if 'sp' in axes and self.sp > 1 and not sp_on:
            raise ValueError(
                "mesh has sp>1 but the model does not declare "
                "_supports_sequence_parallel; sequence-sharding it would "
                "silently train wrong")
        batch_specs = tuple(self._input_spec(i, nd)
                            for i, nd in enumerate(self._batch_ndims))
        self._batch_specs = batch_specs
        if self._overlap:
            pspecs = {'named': {n: self._param_specs[n]
                                for n in self._params},
                      'shards': [P(self._rs_axes)
                                 for _ in self._layout.buckets]}
        else:
            pspecs = self._param_specs
        # on-device LR schedule: the lr argument becomes a device int32
        # step counter; the compiled step derives lr = fn(counter) and
        # returns counter+1 — no per-step host LR compute or H2D feed
        lr_fn = self._lr.fn
        if lr_fn is not None:
            base_step = step

            def step(params, states, step_c, key, *batch):
                out = base_step(params, states,
                                lr_fn(step_c).astype(jnp.float32),
                                key, *batch)
                return out[:3] + (step_c + 1,) + out[3:]

        in_specs = (pspecs, self._state_specs, P(), P(),
                    *batch_specs)
        out_specs = (P(), pspecs, self._state_specs)
        if lr_fn is not None:
            out_specs = out_specs + (P(),)
        if taps_on:
            names = list(self._names)
            out_specs = out_specs + (_num.taps_spec(
                {'grads': dict.fromkeys(names, 0),
                 'params': dict.fromkeys(names, 0),
                 'grad_norm_sq': 0}),)
        mapped = shard_map(step, mesh=self.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
        return jax.jit(mapped, donate_argnums=(0, 1))

    def _update_one(self, p, g, st, lr):
        """Per-shard optimizer update with fp32 master handling (the same
        rule functional_apply uses, inlined for shard-level application)."""
        opt = self.optimizer
        low = p.dtype != jnp.float32
        master = st.pop('master', None)
        p32 = master if master is not None else (
            p.astype(jnp.float32) if low else p)
        g32 = g.astype(jnp.float32)
        wd = getattr(opt, '_weight_decay', None)
        if wd and opt._decay_into_grad():
            g32 = g32 + wd * p32
        if not st:
            st = opt.init_state(Tensor(p32))
        np_, ns = opt.update(p32, g32, st, lr)
        ns = dict(ns)
        if master is not None or (low and getattr(opt, '_multi_precision',
                                                  True)):
            ns['master'] = np_
        return np_.astype(p.dtype), ns

    # -- public ---------------------------------------------------------------
    def _dispatch(self, batch):
        """Dispatch one compiled step; returns an AsyncResult holding
        the device-resident loss (+ taps) — no host fetch."""
        arrays = tuple(b.data if isinstance(b, Tensor) else jnp.asarray(b)
                       for b in batch)
        ddeg = self.dp * self.sharding_deg
        for i, a in enumerate(arrays):
            if a.ndim >= 1 and a.shape[0] % ddeg != 0:
                raise ValueError(
                    f"batch arg {i} has leading dim {a.shape[0]}, not "
                    f"divisible by dp*sharding = {self.dp}*"
                    f"{self.sharding_deg} = {ddeg} (ZeRO 'sharding' "
                    f"ranks are data-parallel ranks)")
        self._ensure_open()
        if arrays:
            self._ledger.observe_batch(arrays[0].shape)
        # gap bracket opens BEFORE any jax client call (key fold-in, lr
        # placement can serialize behind in-flight compute — that time
        # belongs to the dispatch, not the inter-dispatch host gap)
        self._gap.dispatch_begin()
        from ....core import memory as _mem
        first = self._compiled is None   # this dispatch will XLA-compile
        if self._compiled is None:
            self._batch_ndims = tuple(a.ndim for a in arrays)
            with _mem.phase('pipeline.build'):
                self._compiled = self._build()
        lr = self._lr.arg()
        key = rng_mod.next_key()
        p_arg = {'named': self._params, 'shards': self._param_shards} \
            if self._overlap else self._params
        args = (p_arg, self._states, lr, key) + arrays
        if first:
            # explicit AOT compile: lower/compile spans + compile
            # seconds AND the buffer-assignment activation census
            # (ptpu_mem_activation_bytes — the resident bytes the remat
            # policy shrinks; docs/performance.md#remat-policy)
            from .... import profiler as _prof
            self._exec, _ = _prof.compile_with_telemetry(
                self._compiled, 'hybrid.step', args)
        with self._step_guard(first, 'hybrid.train_step', 'hybrid.step'):
            try:
                out = self._exec(*args)
            except TypeError:
                # AOT signature drift (e.g. a new batch shape): fall
                # back to the jitted fn, which retraces per signature
                if self._exec is self._compiled:
                    raise
                self._exec = self._compiled
                out = self._exec(*args)
        self._gap.dispatch_end(depth=len(self._inflight) + 1)
        loss, p_out, self._states = out[:3]
        i = 3
        if self._lr.fn is not None:
            self._lr.carry = out[i]
            i += 1
        taps = out[i] if getattr(self, '_taps_on', False) else None
        if self._overlap:
            self._params = p_out['named']
            self._param_shards = p_out['shards']
        else:
            self._params = p_out
        step_no = self._step_count
        self._step_count += 1
        on_drain = None
        if taps is not None:
            def on_drain(res, _t=taps, _s=step_no):
                self._process_taps(_t, 'hybrid', step=_s)
        return A_.AsyncResult(loss, step_no, taps=taps,
                              on_drain=on_drain, monitor=self._gap)

    def __call__(self, *batch):
        if len(self._inflight):
            # mixed APIs: drain queued async steps FIRST so deferred
            # work (taps/scaler accounting) keeps submission order
            self.flush()
        res = self._dispatch(batch)
        res.wait()     # legacy per-step semantics: taps processed now
        return Tensor(res.loss)

    def train_step(self, *batch):
        """Async dispatch (docs/performance.md#async-dispatch): returns
        an AsyncResult (device-resident loss, no host fetch); a bounded
        in-flight window (PTPU_DISPATCH_WINDOW) lets the host run ahead,
        draining the oldest step — and its deferred taps work — as the
        window fills. `flush()` drains everything."""
        return self._inflight.push(self._dispatch(batch))

    # -- DeviceLoader contract ------------------------------------------------
    def _input_spec(self, idx, nd):
        dp_name = self._batch_axes if self._batch_axes else None
        shard_seq = self._sp_on and nd >= 2 and (
            self.sp_shard_args is None or idx in self.sp_shard_args)
        if shard_seq:
            return P(dp_name, 'sp')
        return P(dp_name) if dp_name else P()

    def input_sharding(self, index, ndim):
        """NamedSharding for batch argument `index` — the spec the
        compiled step expects, so DeviceLoader's background H2D lands
        batches pre-sharded."""
        return NamedSharding(self.mesh, self._input_spec(index, ndim))

    def _process_taps(self, taps, site, step=None):
        """One host sync for the step's stats pytree; publishes
        ptpu_num_* gauges and raises NumericsError on nonfinite grads
        (FLAGS_check_nan_inf) naming the offending parameter."""
        from ....core import numerics as _num
        meta = {'grads': {n: (p.data.shape, p.data.dtype)
                          for n, p in self._params_by_name.items()},
                'params': {n: (p.data.shape, p.data.dtype)
                           for n, p in self._params_by_name.items()}}
        self.last_numerics = _num.process_jit_taps(
            taps, site=site,
            step=self._step_count if step is None else step, meta=meta)

    def _host_bucket_params(self):
        """{name: host array} for bucketed slots, reconstructed from
        the flat param shards (overlap mode). These are the EXACT
        updated values — under an int8 wire the compiled forward sees
        the block-rounded gathered copy, but the shards (backed by the
        sharded fp32 master) are the trajectory, so checkpoints and
        sync_model round-trip without wire rounding
        (docs/performance.md#comm-overlap)."""
        out = {}
        for b, sh in zip(self._layout.buckets, self._param_shards):
            host = np.asarray(jax.device_get(sh))
            for s in b.slots:
                out[s.name] = host[s.offset:s.offset + s.size] \
                    .reshape(s.shape)
        return out

    def sync_model(self):
        """Write updated params back into the eager Layer. Drains the
        async dispatch window first so every dispatched step is
        reflected (docs/performance.md#async-dispatch drain semantics)."""
        self._ensure_open()
        self.flush()
        for n, arr in self._params.items():
            self._params_by_name[n]._data = arr
        if self._overlap:
            for n, arr in self._host_bucket_params().items():
                self._params_by_name[n]._data = jnp.asarray(arr)

    # shutdown()/close() from EngineTeardown

    @property
    def params(self):
        return self._params

    # -- checkpoint (parity: fleet.save/set_state_dict re-broadcast flow,
    # SURVEY.md §5.4) --------------------------------------------------------
    def state_dict(self):
        """Checkpoint in the stable PER-PARAMETER schema regardless of
        the runtime state layout: flat sharded bucket states are
        converted back through the layout map, so a checkpoint written
        by a bucketed engine restores into a legacy one and vice
        versa."""
        import numpy as _np
        import jax as _jax
        self.flush()        # checkpoints see every dispatched step
        out = {'params': {}, 'states': {}}
        for n, a in self._params.items():
            out['params'][n] = _np.asarray(_jax.device_get(a))
        if self._overlap:
            for n, a in self._host_bucket_params().items():
                out['params'][n] = _np.asarray(a)
        for n, st in self._states['named'].items():
            out['states'][n] = {k: _np.asarray(_jax.device_get(v))
                                for k, v in st.items()}
        if self._bucketed:
            host_flat = [{k: _np.asarray(_jax.device_get(v))
                          for k, v in st.items()}
                         for st in self._states['buckets']]
            out['states'].update(
                B.flat_states_to_named(self._layout, host_flat))
        out['step'] = self._step_count
        return out

    def set_state_dict(self, sd):
        import numpy as _np
        import jax as _jax
        for n, a in sd['params'].items():
            if n in self._params:
                self._params[n] = self._place(a, self._param_specs[n])
        if self._overlap:
            # rebuild the flat param shards from the per-param schema
            # (missing params keep their current shard values)
            shard_spec = P(self._rs_axes)
            for i, b in enumerate(self._layout.buckets):
                host = _np.array(
                    _jax.device_get(self._param_shards[i]), copy=True)
                touched = False
                for s in b.slots:
                    if s.name in sd['params']:
                        host[s.offset:s.offset + s.size] = _np.asarray(
                            sd['params'][s.name]).reshape(-1) \
                            .astype(host.dtype)
                        touched = True
                if touched:
                    self._param_shards[i] = self._place_flat(
                        host, shard_spec)
        named_sd = dict(sd.get('states', {}))
        if self._bucketed:
            template = [{k: _np.asarray(_jax.device_get(v))
                         for k, v in st.items()}
                        for st in self._states['buckets']]
            flat = B.named_states_to_flat(
                self._layout,
                {n: named_sd.pop(n) for n in list(named_sd)
                 if n in self._layout.slots},
                template)
            for i, st in enumerate(flat):
                for k, v in st.items():
                    spec = self._state_specs['buckets'][i][k]
                    self._states['buckets'][i][k] = (
                        self._place_flat(v, spec) if _np.ndim(v) >= 1
                        else self._place(v, spec))
        for n, st in named_sd.items():
            if n in self._states['named']:
                for k, v in st.items():
                    if k in self._state_specs['named'][n]:
                        self._states['named'][n][k] = self._place(
                            v, self._state_specs['named'][n][k])
        self._step_count = sd.get('step', 0)
        if self._lr.fn is not None:
            # re-sync the device LR counter to the (restored) host
            # scheduler's epoch — resume mid-schedule lands on the same
            # lr the host path would feed next
            self._lr.reset_carry()
