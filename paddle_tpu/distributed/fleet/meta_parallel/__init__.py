"""fleet.meta_parallel (parity: fleet/meta_parallel/__init__.py)."""
from .parallel_layers import (VocabParallelEmbedding, ColumnParallelLinear,
                              RowParallelLinear, ParallelCrossEntropy,
                              LayerDesc, SharedLayerDesc, PipelineLayer,
                              RNGStatesTracker, get_rng_state_tracker,
                              model_parallel_random_seed)
from .meta_parallel_base import MetaParallelBase
from .pipeline_parallel import PipelineParallel
from .tensor_parallel import TensorParallel
from .sharding_parallel import ShardingParallel
