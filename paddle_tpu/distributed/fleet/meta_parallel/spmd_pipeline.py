"""SPMD pipeline-parallel engine (dp × pp × mp in ONE compiled program).

Reference parity: the semantics of PipelineTrainer/SectionWorker
(section_worker.cc:104-185 — microbatch schedules), PipelineParallel
.train_batch (pipeline_parallel.py:114 — F-then-B over microbatches with p2p
sends), 1F1B's steady-state utilization, gradient accumulation over
microbatches (optimizer.py _accumulate_gradients:4974), and tied-weight grad
sync (A.4 allreduce_shared_weight_gradients).

TPU-native design (no host round-trips per microbatch — SURVEY.md §7 hard
part (a)):
  * every stage's transformer blocks are ONE stacked parameter pytree
    [num_layers, ...] sharded over the 'pp' mesh axis → each device holds its
    stage's [layers_per_stage, ...] slice and runs them with a local
    `lax.scan` (weight-stationary);
  * the microbatch clock is a `lax.scan` over A + P - 1 ticks; activations
    move between neighbor stages with `lax.ppermute` over ICI — the
    CollectivePermute replacement for send_v2/recv_v2 NCCL pairs;
  * stage-dependent behavior (ingest on stage 0, loss on last stage) is
    `jnp.where` masking — SPMD-uniform code, XLA-friendly;
  * three schedules: '1F1B' (default) and 'F-then-B' match
    section_worker.cc:134-185's schedule_mode pair — '1F1B'
    hand-interleaves one forward + one backward sub-step per tick with a
    circular O(pp) stage-input buffer and per-tick local `jax.vjp` (see
    _build_1f1b); 'F-then-B' takes `jax.grad` through the whole tick
    scan — scan transposition yields the reverse pipeline automatically,
    at O(A) boundary-activation cost — with `jax.checkpoint` on the
    block fn for activation recompute; 'interleaved' is the Megatron
    virtual-stage schedule (arXiv:2104.04473): each physical stage holds
    `virtual_stages` model chunks split round-robin, so every masked
    warm-up/drain tick burns 1/v of a stage and the bubble shrinks
    ~1/v (see _build_interleaved + schedule_model);
  * embedding/head weights are replicated over 'pp'; their grads get
    psum('pp') — exactly allreduce_shared_weight_gradients;
  * dp grad sync = pmean over 'dp'; mp collectives run inside blocks.
"""
import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P, NamedSharding
from jax.experimental.shard_map import shard_map

from ....core import rng as rng_mod
from ....core import autograd
from ....core import async_step as A_
from ....core import bucketing as B
from ....core.tensor import Tensor
from ....jit import bind_arrays
from ... import collective as C
from ... import topology_runtime


def _spec_for(p, axes, extra_leading_pp=False):
    nd = len(p.data.shape) + (1 if extra_leading_pp else 0)
    spec = [None] * nd
    if extra_leading_pp:
        spec[0] = 'pp'
    if getattr(p, 'is_distributed', False) and 'mp' in axes:
        spec[p.split_axis + (1 if extra_leading_pp else 0)] = 'mp'
    return P(*spec)


class PipelineScheduleError(ValueError):
    """A pipeline-schedule configuration the engine cannot honor
    (layer/chunk divisibility, virtual stages on a schedule without
    them, accumulate_steps not forming whole microbatch groups)."""


class PipelineBatchError(ValueError):
    """A batch whose shape cannot be microbatched by the engine
    (size not divisible by dp x accumulate_steps, or an input/label
    leading-dimension mismatch)."""


def resolve_virtual_stages(virtual_stages=None, from_layer=None):
    """Virtual-stage count resolution (docs/performance.md
    #pipeline-schedules): explicit kwarg -> PTPU_PP_VIRTUAL env ->
    PipelineLayer(num_virtual_pipeline_stages=) -> None (unset)."""
    if virtual_stages is not None:
        return int(virtual_stages)
    env = os.environ.get('PTPU_PP_VIRTUAL')
    if env:
        try:
            return int(env)
        except ValueError:
            raise PipelineScheduleError(
                f"PTPU_PP_VIRTUAL={env!r} is not an integer")
    if from_layer is not None:
        return int(from_layer)
    return None


def chunk_layer_order(num_layers, pp, virtual_stages):
    """Round-robin layer -> (stage, chunk) assignment (arXiv:2104.04473
    interleaved schedule): global model chunk g = c*pp + s holds layers
    [g*per, (g+1)*per) with per = num_layers/(pp*v). Returns the
    STACKING order: row i of the [num_layers, ...] stacked block tree
    holds original layer order[i], so the P('pp') shard of device s is
    exactly its v chunks, chunk-major. Identity when v == 1."""
    pp = max(int(pp), 1)
    v = max(int(virtual_stages or 1), 1)
    if num_layers % (pp * v) or num_layers < pp * v:
        raise PipelineScheduleError(
            f"{num_layers} layers cannot split round-robin into "
            f"pp({pp}) x virtual_stages({v}) = {pp * v} non-empty "
            f"chunks; pick num_layers divisible by pp*virtual_stages "
            f"(PipelineLayer(num_virtual_pipeline_stages=) / "
            f"virtual_stages= / PTPU_PP_VIRTUAL)")
    per = num_layers // (pp * v)
    return [(c * pp + s) * per + i
            for s in range(pp) for c in range(v) for i in range(per)]


def _sim_inflight(pp, A, v):
    """Walk the interleaved-1F1B tick table: per-chunk residual slots
    needed (closed write..read interval, the same-tick write-then-read
    counts as live) and the peak in-flight microbatch count per device.
    v=1 reproduces the classic 1F1B window min(A, 2*pp-1). Each
    chunk's live set is a contiguous ascending-m window (both job
    streams are monotone in m), so a two-pointer per chunk plus one
    event sweep per stage does it in O(pp * (A*v + T))."""
    ppv = pp * v
    D = 2 * (pp - 1) + (v - 1) * pp
    T = A * v + D

    slots = 1
    peak = 0
    for s in range(pp):
        def t_fwd(c, m):
            r, q = divmod(m, pp)
            return s + r * ppv + c * pp + q

        def t_bwd(c, m):
            r, q = divmod(m, pp)
            return (D - s) + r * ppv + (v - 1 - c) * pp + q

        delta = [0] * (T + 2)
        for c in range(v):
            m0 = 0
            for m in range(A):
                delta[t_fwd(c, m)] += 1
                delta[t_bwd(c, m) + 1] -= 1
                while t_bwd(c, m0) < t_fwd(c, m):
                    m0 += 1
                slots = max(slots, m - m0 + 1)
        live = 0
        for d in delta:
            live += d
            peak = max(peak, live)
    return slots, peak


def schedule_model(schedule, pp, accumulate_steps, virtual_stages=1,
                   memory_mode=None):
    """Static schedule model of ONE compiled pipeline step: tick count,
    executed chunk sub-steps per device, and the modeled bubble
    fraction (masked warm-up/drain work as a fraction of executed
    work). One tick = one chunk forward + one chunk backward sub-step
    per stage in lockstep; a chunk is 1/v of a stage, so interleaving
    shrinks the (pp-1)-tick ramp cost by ~1/v (arXiv:2104.04473):

        bubble_fraction = (pp - 1) / (A*v + pp - 1)

    The forward/backward cond windows in the compiled scan match
    fwd_window/bwd_window exactly; ticks is the lax.scan length."""
    if schedule in ('FThenB', 'F-then-B'):
        schedule = 'F-then-B'
    pp = max(int(pp), 1)
    A = int(accumulate_steps)
    v = max(int(virtual_stages or 1), 1) if schedule == 'interleaved' \
        else 1
    if schedule == 'F-then-B':
        ticks = A + pp - 1          # fwd scan; bwd is its transposition
        warmup = pp - 1
        fwd_w = bwd_w = A + pp - 1
        slots, peak = A, A          # O(A) boundary activations stored
    else:                           # '1F1B' / 'interleaved'
        D = 2 * (pp - 1) + (v - 1) * pp
        ticks = A * v + D
        warmup = D - (pp - 1)       # ticks before the first bwd anywhere
        fwd_w = bwd_w = A * v + pp - 1
        slots, peak = _sim_inflight(pp, A, v)
        slots = min(slots, A)
    useful = 2 * A * v
    chunk_ticks = fwd_w + bwd_w
    model = {
        'schedule': schedule,
        'pp': pp,
        'virtual_stages': v,
        'accumulate_steps': A,
        'ticks': ticks,
        'warmup_ticks': warmup,
        'fwd_window': fwd_w,
        'bwd_window': bwd_w,
        'chunk_ticks': chunk_ticks,
        'useful_chunk_ticks': useful,
        'bubble_fraction': 1.0 - useful / chunk_ticks,
        'inflight_peak': peak,
        'slots_per_chunk': slots,
        # wire-traffic model: two lax.ppermute ring hops per tick (act
        # +1, cotangent -1) — interleaving trades ~v x more boundary
        # crossings for the 1/v ramp (docs/performance.md
        # #pipeline-schedules)
        'ppermute_steps': 2 * ticks if pp > 1 else 0,
    }
    if memory_mode is not None:
        model['memory_mode'] = memory_mode
    return model


def publish_schedule_gauges(model, engine='pipeline'):
    """ptpu_pp_* gauges from a schedule_model() dict through
    core.monitor — StepTelemetry.snapshot()['pipeline'] and
    `tools/health_dump.py pp` read these back."""
    try:
        from ....core.monitor import gauge
    except Exception:
        return
    lbl = {'engine': engine}
    for name, key, help_ in (
            ('ptpu_pp_ticks', 'ticks', 'pipeline scan ticks per step'),
            ('ptpu_pp_chunk_ticks', 'chunk_ticks',
             'executed chunk fwd+bwd sub-steps per device per step'),
            ('ptpu_pp_useful_chunk_ticks', 'useful_chunk_ticks',
             'unmasked chunk sub-steps per device per step'),
            ('ptpu_pp_bubble_fraction', 'bubble_fraction',
             'modeled masked-work fraction of the schedule'),
            ('ptpu_pp_inflight_peak', 'inflight_peak',
             'peak in-flight microbatches per device'),
            ('ptpu_pp_virtual_stages', 'virtual_stages',
             'model chunks per physical stage (v)'),
            ('ptpu_pp_stages', 'pp', 'pipeline-parallel degree'),
            ('ptpu_pp_accumulate_steps', 'accumulate_steps',
             'microbatches per step (A)')):
        gauge(name, help=help_, labelnames=('engine',)).set(
            float(model[key]), **lbl)
    g = gauge('ptpu_pp_schedule_info',
              help='active pipeline schedule (value 1; the schedule '
                   'rides in the label)',
              labelnames=('engine', 'schedule'))
    for other in ('1F1B', 'F-then-B', 'interleaved'):
        g.set(1 if other == model['schedule'] else 0,
              engine=engine, schedule=other)


def pipeline_snapshot(engine='pipeline'):
    """StepTelemetry.snapshot()['pipeline'] payload: the published
    schedule census read back from the ptpu_pp_* gauges (None when no
    pipeline engine has been built)."""
    try:
        from ....core import monitor as _m
        reg = _m.metrics()
        if reg.get('ptpu_pp_ticks') is None:
            return None

        def val(name):
            m = reg.get(name)
            if m is None:
                return None
            for labels, child in m._series().items():
                if labels and labels[0] == engine:
                    return child.value()
            return None

        snap = {
            'ticks': int(val('ptpu_pp_ticks') or 0),
            'chunk_ticks': int(val('ptpu_pp_chunk_ticks') or 0),
            'useful_chunk_ticks':
                int(val('ptpu_pp_useful_chunk_ticks') or 0),
            'bubble_fraction': val('ptpu_pp_bubble_fraction'),
            'inflight_peak': int(val('ptpu_pp_inflight_peak') or 0),
            'virtual_stages': int(val('ptpu_pp_virtual_stages') or 1),
            'pp': int(val('ptpu_pp_stages') or 1),
            'accumulate_steps':
                int(val('ptpu_pp_accumulate_steps') or 0),
        }
        info = reg.get('ptpu_pp_schedule_info')
        if info is not None:
            for labels, child in info._series().items():
                if labels and labels[0] == engine and child.value():
                    snap['schedule'] = labels[1]
        return snap
    except Exception:
        return None


from ....nn.layer.base import Layer as _Layer
from ....nn.layer.container import LayerList as _LayerList


class _FnLayer(_Layer):
    """Parameterless adapter for plain-callable pipeline descs."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _HeadWrapper(_Layer):
    """Adapts (tail layers + loss_fn) into the engine's head(hidden, labels)
    contract. A loss_fn that owns trainable parameters must itself be an
    nn.Layer (so the engine can lift them); a plain closure capturing
    parameters would silently bake them as compile-time constants."""

    def __init__(self, tail_layers, loss_fn):
        super().__init__()
        self.tail = _LayerList([
            t if isinstance(t, _Layer) else _FnLayer(t)
            for t in tail_layers])
        if isinstance(loss_fn, _Layer):
            self.loss_layer = loss_fn
            self._loss_call = loss_fn
        else:
            self._loss_call = loss_fn

    def forward(self, hidden, labels):
        x = hidden
        for layer in self.tail:
            x = layer(x)
        return self._loss_call(x, labels)


def engine_from_pipeline_layer(pipeline_layer, optimizer, accumulate_steps,
                               mesh=None, use_remat=True, schedule='1F1B',
                               remat_policy=None, virtual_stages=None):
    """Build a SpmdPipelineEngine from a PipelineLayer's descs (parity: the
    dygraph PipelineParallel engine construction from pp_layers).

    Convention: desc[0] is the embedding/input stage, the trailing
    non-uniform descs (e.g. final norm) plus the PipelineLayer's loss_fn
    form the head, and the uniform middle run becomes the stacked blocks.

    `PipelineLayer(num_virtual_pipeline_stages=)` is honored here: a
    value > 1 (or virtual_stages=/PTPU_PP_VIRTUAL) selects the
    interleaved schedule; values the uniform block run cannot split
    into pp*v non-empty chunks raise PipelineScheduleError.
    """
    funcs, shared = pipeline_layer.build_full_model()
    if pipeline_layer._loss_fn is None:
        raise ValueError("PipelineLayer needs loss_fn for SPMD training")
    if len(funcs) < 2:
        raise ValueError("pipeline model too small to split: need "
                         "embed + blocks")
    # Tied weights across segments would silently untie here (embed and head
    # trees get independent arrays) — refuse rather than train a wrong
    # parameterization. Untied heads (GPTLMHead pattern) are the supported
    # shape; single-segment sharing is fine.
    uses = {}
    for f in funcs:
        for key, layer in shared.items():
            if f is layer or getattr(f, 'func', None) is layer \
                    or getattr(f, '__self__', None) is layer:
                uses[key] = uses.get(key, 0) + 1
    multi = [k for k, c in uses.items() if c > 1]
    if multi:
        raise NotImplementedError(
            f"SharedLayerDesc keys {multi} are used by multiple pipeline "
            "segments; cross-stage weight tying is not supported by the "
            "SPMD pipeline engine yet — use an untied head "
            "(e.g. models.gpt.GPTLMHead / build_gpt_pipeline)")

    embed = funcs[0]

    def sig(layer):
        if not hasattr(layer, 'named_parameters'):
            return None
        return tuple(sorted((n, tuple(p.shape))
                            for n, p in layer.named_parameters())) or None

    # find the maximal uniform run starting at funcs[1]
    base = sig(funcs[1]) if len(funcs) > 1 else None
    if base is None:
        raise ValueError("desc[1] must be the first transformer block (a "
                         "Layer with parameters); got "
                         f"{type(funcs[1]).__name__}")
    end = 1
    while end < len(funcs) and sig(funcs[end]) == base:
        end += 1
    blocks = funcs[1:end]
    tail = funcs[end:]
    head = _HeadWrapper(tail, pipeline_layer._loss_fn)
    # honor the PipelineLayer's recompute_interval: a nonzero interval is
    # the dygraph-parity opt-in for activation recompute, so it forces
    # remat ON for the compiled engine (the trace-level twin of wrapping
    # every k-th layer in fleet.utils.recompute) — the resolved policy
    # then decides what is saved vs recomputed
    if getattr(pipeline_layer, '_recompute_interval', 0):
        use_remat = True
    # wire the long-silently-ignored num_virtual_pipeline_stages
    # (kwarg -> PTPU_PP_VIRTUAL -> the PipelineLayer's own value); the
    # engine validates divisibility and schedule compatibility
    v = resolve_virtual_stages(
        virtual_stages,
        from_layer=getattr(pipeline_layer,
                           '_num_virtual_pipeline_stages', None))
    return SpmdPipelineEngine(embed, blocks, head, optimizer,
                              accumulate_steps, mesh=mesh,
                              use_remat=use_remat, schedule=schedule,
                              remat_policy=remat_policy,
                              virtual_stages=v)


from .meta_parallel_base import EngineTeardown


class SpmdPipelineEngine(A_.AsyncDispatchMixin, EngineTeardown):
    """Pipelined hybrid train step.

    Args:
      embed: Layer mapping (input_ids) -> activations [mb, L, H]; params
        replicated over pp (tied-weight psum applies).
      blocks: list of num_layers structurally-identical Layers.
      head: Layer mapping (activations, labels) -> per-microbatch scalar
        loss (final norm + LM head + criterion).
      optimizer: paddle_tpu Optimizer (functional update rules reused).
      accumulate_steps: number of microbatches A.
    """

    def __init__(self, embed, blocks, head, optimizer, accumulate_steps,
                 mesh=None, use_remat=True, schedule='1F1B',
                 grad_accum_dtype='float32', memory_mode='stash',
                 use_buckets=None, comm_dtype=None, bucket_mb=None,
                 comm_block=None, comm_overlap=None, prefetch_depth=None,
                 comm_chunk=None, remat_policy=None,
                 dispatch_window=None, device_lr=None,
                 virtual_stages=None):
        self.embed = embed
        self.blocks = blocks
        self.head = head
        self.optimizer = optimizer
        self.A = accumulate_steps
        # tuned remat (docs/performance.md#remat-policy): a resolved
        # policy (kwarg -> PTPU_REMAT_POLICY -> strategy) overrides the
        # schedule-specific legacy split (full remat / save-dots) that
        # `use_remat=True` alone picks in _make_stage_forward
        from ..utils.recompute import resolve_policy as _resolve_remat
        self._remat_policy = _resolve_remat(remat_policy,
                                                       default=None)
        if self._remat_policy is not None:
            use_remat = self._remat_policy != 'none'
        self.use_remat = use_remat
        # 1F1B backward source: 'stash' (default) keeps each in-flight
        # microbatch's vjp residuals — the reference SectionWorker's
        # store-activations schedule (section_worker.cc:147-184) — so
        # backward never re-runs the stage forward; 'recompute' keeps only
        # the stage INPUT per in-flight microbatch and re-derives the
        # residuals inside the backward tick (lower memory, +1 fwd FLOPs).
        if memory_mode not in ('stash', 'recompute'):
            raise ValueError(f"memory_mode must be 'stash' or 'recompute', "
                             f"got {memory_mode!r}")
        self.memory_mode = memory_mode
        # 1F1B microbatch-grad accumulator dtype: float32 (default) or
        # 'param' to accumulate in the parameter dtype — halves the
        # accumulator HBM for bf16 models when memory-bound (single-chip
        # 1.3B); fine for small accumulate_steps
        self.grad_accum_dtype = grad_accum_dtype
        if schedule in ('FThenB', 'F-then-B'):
            schedule = 'F-then-B'
        elif schedule not in ('1F1B', 'interleaved'):
            raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                             "expected '1F1B', 'F-then-B' or "
                             "'interleaved'")
        # virtual stages (arXiv:2104.04473 interleaved schedule):
        # kwarg -> PTPU_PP_VIRTUAL -> PipelineLayer wiring (via
        # engine_from_pipeline_layer). v > 1 upgrades the default 1F1B
        # to 'interleaved'; F-then-B has no virtual-stage formulation.
        vv = resolve_virtual_stages(virtual_stages)
        if vv is not None and vv < 1:
            raise PipelineScheduleError(
                f"virtual_stages must be >= 1, got {vv}")
        if schedule == 'interleaved':
            self.vp = vv if vv is not None else 2
        elif vv is not None and vv > 1:
            if schedule == 'F-then-B':
                raise PipelineScheduleError(
                    f"schedule 'F-then-B' cannot honor virtual_stages="
                    f"{vv} (num_virtual_pipeline_stages/PTPU_PP_VIRTUAL"
                    "); use schedule='interleaved' or '1F1B'")
            schedule = 'interleaved'
            self.vp = vv
        else:
            self.vp = 1
        self.schedule = schedule
        self._use_scaling = False     # fp16 GradScaler path (compile-time)
        self.mesh = mesh if mesh is not None else topology_runtime.get_mesh()
        if self.mesh is None:
            raise ValueError("no mesh registered")
        self.axes = tuple(self.mesh.axis_names)
        self.pp = self.mesh.shape.get('pp', 1)
        self.dp = self.mesh.shape.get('dp', 1)
        # stacking order: row i of the stacked [L, ...] block trees
        # holds blocks[self._layer_order[i]] — identity for 1F1B /
        # F-then-B, round-robin chunk-major for interleaved so each
        # P('pp') shard is its stage's v chunks back to back. Raises
        # PipelineScheduleError (naming the knobs) when the layers
        # cannot split into pp*v non-empty chunks.
        self._layer_order = chunk_layer_order(
            len(blocks), self.pp, self.vp)
        if self.vp > 1 and accumulate_steps % max(self.pp, 1):
            raise PipelineScheduleError(
                f"interleaved schedule needs accumulate_steps("
                f"{accumulate_steps}) divisible by pp("
                f"{max(self.pp, 1)}): microbatches advance in groups "
                "of pp per model chunk (arXiv:2104.04473)")
        # static schedule model + census (ptpu_pp_* gauges ->
        # StepTelemetry.snapshot()['pipeline'], health_dump pp): the
        # compiled scan's tick count and cond windows follow this model
        # exactly, so the bubble shrink is a measured number
        self._sched_model = schedule_model(
            self.schedule, self.pp, self.A, self.vp,
            memory_mode=memory_mode)
        publish_schedule_gauges(self._sched_model, engine='pipeline')

        # -- parameter pytrees ------------------------------------------------
        self._embed_named = [(n, p) for n, p in embed.named_parameters()
                             if not p.stop_gradient]
        self._head_named = [(n, p) for n, p in head.named_parameters()
                            if not p.stop_gradient]
        self._block_named = [(n, p) for n, p in blocks[0].named_parameters()
                             if not p.stop_gradient]

        embed_specs = {n: _spec_for(p, self.axes)
                       for n, p in self._embed_named}
        head_specs = {n: _spec_for(p, self.axes)
                      for n, p in self._head_named}
        block_specs = {n: _spec_for(p, self.axes, extra_leading_pp=True)
                       for n, p in self._block_named}

        from ....core import memory as _mem
        with _mem.phase('engine.init'):
            stacked = {}
            for n, p0 in self._block_named:
                per_layer = []
                for j in self._layer_order:
                    per_layer.append(
                        dict(blocks[j].named_parameters())[n].data)
                stacked[n] = jnp.stack(per_layer, axis=0)  # [L, ...]

            self._specs = {'embed': embed_specs, 'blocks': block_specs,
                           'head': head_specs}
            self._params = {
                'embed': {n: self._place(p.data, embed_specs[n])
                          for n, p in self._embed_named},
                'blocks': {n: self._place(stacked[n], block_specs[n])
                           for n, p0 in self._block_named},
                'head': {n: self._place(p.data, head_specs[n])
                         for n, p in self._head_named},
            }
            # shapes snapshot for taps meta (overlap mode later moves
            # bucketed slots out of the group trees)
            self._tap_shapes = {
                f'{grp}/{n}': (tuple(a.shape), a.dtype)
                for grp in ('embed', 'blocks', 'head')
                for n, a in self._params[grp].items()}

            # -- bucketed rs/ag weight-update sharding over 'dp'
            # (arXiv:2004.13336): grads coalesce into flat buckets, each
            # dp rank owns a 1/dp shard of params+moments. Blocks are
            # stage-LOCAL (their buckets key separately and their flat
            # states carry a leading pp dim); mp-sharded params keep the
            # per-param path.
            self.comm_dtype, self._bucket_bytes = B.resolve_comm_config(
                comm_dtype, bucket_mb)
            self._comm_block = B.resolve_comm_block(comm_block)
            # comm/compute overlap (ISSUE 10): deferred/prefetched param
            # all-gather + chunked collectives over 'dp' (the pipeline's
            # grads only complete at scan end, so the eager-rs leg of
            # the overlap story is the hybrid engine's; here the win is
            # the gather moved under the next step's forward + the
            # sharded resident param set)
            overlap_req, self._prefetch_depth, self._comm_chunk = \
                B.resolve_overlap_config(comm_overlap, prefetch_depth,
                                         comm_chunk)
            dp_on_init = 'dp' in self.axes and self.mesh.shape['dp'] > 1
            self._pp_layout = None
            mp_on = 'mp' in self.axes and self.mesh.shape['mp'] > 1
            if B.elementwise(optimizer):
                local_shapes = {}
                for grp, named_list in (('embed', self._embed_named),
                                        ('blocks', self._block_named),
                                        ('head', self._head_named)):
                    for n, p in named_list:
                        if getattr(p, 'is_distributed', False) and mp_on:
                            continue
                        shp = tuple(p.data.shape)
                        if grp == 'blocks':
                            shp = (len(blocks) // max(self.pp, 1),) + shp
                        local_shapes[f'{grp}/{n}'] = (shp, p.data.dtype)
                if local_shapes:
                    self._pp_layout = B.BucketLayout.build(
                        local_shapes, bucket_bytes=self._bucket_bytes,
                        pad_to=max(self.dp, 1) * 8,
                        group_fn=lambda name, shape, dtype:
                            'blocks' if name.startswith('blocks/')
                            else 'repl')
            self._pp_bucketed = bool(
                self._pp_layout is not None and dp_on_init
                and use_buckets is not False)
            self._pp_overlap = bool(overlap_req and self._pp_bucketed)
            if self._pp_overlap:
                B.ensure_overlap_xla_flags()
            if self._pp_layout is not None:
                accum_fp32 = self.grad_accum_dtype != 'param'
                B.publish_comm_gauges(
                    self._pp_layout, engine='pipeline',
                    n_shards=max(self.dp, 1),
                    comm_dtype=self.comm_dtype or (
                        jnp.float32 if accum_fp32 else None),
                    enabled=self._pp_bucketed,
                    block=self._comm_block)
                B.publish_overlap_gauges(
                    self._pp_layout, engine='pipeline',
                    n_shards=max(self.dp, 1),
                    comm_dtype=self.comm_dtype or (
                        jnp.float32 if accum_fp32 else None),
                    enabled=self._pp_overlap,
                    prefetch=self._prefetch_depth,
                    chunk=self._comm_chunk,
                    block=self._comm_block)
            if not self._pp_bucketed:
                self._pp_layout = None
            if self._pp_overlap:
                # deferred gather: bucketed params live as [pp, size/dp]
                # shards between steps; the full trees only exist inside
                # the step (materialized group-by-group before use)
                self._build_param_shards(stacked)

            # optimizer state mirrors the param tree (per-param states
            # only for params outside the bucket layout)
            self._states = {}
            self._state_specs = {}
            in_layout = set(self._pp_layout.slots) if self._pp_bucketed \
                else set()
            for grp in ('embed', 'blocks', 'head'):
                self._states[grp] = {}
                self._state_specs[grp] = {}
                for n, arr in self._params[grp].items():
                    if f'{grp}/{n}' in in_layout:
                        continue
                    st = {}
                    sspec = {}
                    tmpl = optimizer.init_state(Tensor(
                        jnp.zeros(arr.shape, jnp.float32)))
                    if arr.dtype != jnp.float32 and getattr(
                            optimizer, '_multi_precision', True):
                        tmpl['master'] = arr.astype(jnp.float32)
                    for k, v in tmpl.items():
                        spec = self._specs[grp][n] if (
                            np.ndim(v) >= 1 and v.shape == arr.shape) else (
                            P('pp') if grp == 'blocks' and np.ndim(v) >= 1
                            else P())
                        if grp == 'blocks' and np.ndim(v) == 0:
                            # scalars (beta powers) per stacked tree stay
                            # scalar
                            spec = P()
                        st[k] = self._place(v, spec)
                        sspec[k] = spec
                    self._states[grp][n] = st
                    self._state_specs[grp][n] = sspec
            self._states['_buckets'] = []
            self._state_specs['_buckets'] = []
            if self._pp_bucketed:
                self._init_flat_states(stacked)

        self._compiled = None
        self._closed = False
        self._grad_clip = optimizer._grad_clip

        # -- async step pipeline (ISSUE 13,
        # docs/performance.md#async-dispatch) --------------------------------
        self._inflight = A_.DispatchWindow(
            A_.resolve_dispatch_window(dispatch_window))
        self._gap = A_.HostGapMonitor('pipeline')
        # step-time ledger (ISSUE 16): wall decomposition (incl. the
        # modeled schedule bubble) + model-FLOPs accounting. The FLOPs
        # remat factor: a resolved policy wins; else the legacy split —
        # 'recompute' memory mode re-runs stage forwards ('full'),
        # stash-1F1B keeps residuals with a save-dots backward ('dots')
        from ....core import ledger as _led
        self._ledger = _led.StepLedger(
            'pipeline', gap=self._gap,
            params_fn=lambda: _led.count_params(self._params),
            remat_policy=self._remat_policy or (
                'full' if self.memory_mode == 'recompute'
                else ('dots' if self.use_remat else 'none')),
            bubble_fraction_fn=lambda: self._sched_model.get(
                'bubble_fraction', 0.0))
        from ....optimizer import device_lr as _dlr
        self._lr = _dlr.LrFeed(optimizer, device_lr,
                               place=lambda a: self._place(a, P()))

    def _init_flat_states(self, stacked):
        """Flat sharded optimizer state per bucket. Every vector state is
        a GLOBAL [pp, bucket_size] array sharded P('pp' on dim 0, 'dp'
        on dim 1): each device holds the [1, size/dp] shard it updates.
        Stage-local (blocks) buckets genuinely differ along pp;
        replicated (embed/head) buckets carry identical rows — same
        per-device bytes either way, and one uniform spec."""
        opt = self.optimizer
        pp = max(self.pp, 1)
        pp_ax = 'pp' if 'pp' in self.axes else None
        vec_spec = P(pp_ax, 'dp')
        for b in self._pp_layout.buckets:
            # host-side initial fp32 values, per stage row
            flat32 = np.zeros((pp, b.size), np.float32)
            for s in b.slots:
                grp, n = s.name.split('/', 1)
                if grp == 'blocks':
                    arr = np.asarray(jax.device_get(stacked[n]), np.float32)
                    per = arr.shape[0] // pp
                    for k in range(pp):
                        flat32[k, s.offset:s.offset + s.size] = \
                            arr[k * per:(k + 1) * per].reshape(-1)
                else:
                    named = dict(self._embed_named if grp == 'embed'
                                 else self._head_named)
                    row = np.asarray(jax.device_get(named[n].data),
                                     np.float32).reshape(-1)
                    flat32[:, s.offset:s.offset + s.size] = row
            st = B.init_bucket_state(
                opt, b, flat32[0],
                force_master=B._is_int8(self.comm_dtype))
            placed, sspec = {}, {}
            for k, v in st.items():
                if np.ndim(v) >= 1:
                    host = flat32 if k == 'master' else np.broadcast_to(
                        np.asarray(v), (pp, b.size))
                    sharding = NamedSharding(self.mesh, vec_spec)
                    placed[k] = jax.make_array_from_callback(
                        host.shape, sharding,
                        lambda idx, _h=host: _h[idx])
                    sspec[k] = vec_spec
                else:
                    placed[k] = self._place(v, P())
                    sspec[k] = P()
            self._states['_buckets'].append(placed)
            self._state_specs['_buckets'].append(sspec)

    def _build_param_shards(self, stacked):
        """Overlap mode: move every bucketed param out of the group
        trees into flat [pp, bucket_size] arrays sharded P('pp','dp')
        — each device keeps only the [1, size/dp] slice it updates.
        Blocks rows are stage-local; embed/head rows replicate (same
        per-device bytes, one uniform spec — the flat-state layout)."""
        pp = max(self.pp, 1)
        pp_ax = 'pp' if 'pp' in self.axes else None
        spec = P(pp_ax, 'dp')
        layout = self._pp_layout
        shards = []
        for b in layout.buckets:
            host = np.zeros((pp, b.size), b.dtype)
            for s in b.slots:
                grp, n = s.name.split('/', 1)
                if grp == 'blocks':
                    arr = np.asarray(jax.device_get(stacked[n]))
                    per = arr.shape[0] // pp
                    for k in range(pp):
                        host[k, s.offset:s.offset + s.size] = \
                            arr[k * per:(k + 1) * per].reshape(-1) \
                            .astype(b.dtype)
                else:
                    named = dict(self._embed_named if grp == 'embed'
                                 else self._head_named)
                    row = np.asarray(
                        jax.device_get(named[n].data)).reshape(-1) \
                        .astype(b.dtype)
                    host[:, s.offset:s.offset + s.size] = row
            sharding = NamedSharding(self.mesh, spec)
            shards.append(jax.make_array_from_callback(
                host.shape, sharding, lambda idx, _h=host: _h[idx]))
        for s in layout.slots.values():
            grp, n = s.name.split('/', 1)
            self._params[grp].pop(n, None)
            self._specs[grp].pop(n, None)
        self._params['_shards'] = shards
        self._specs['_shards'] = [spec] * len(shards)

    def _materialize_params(self, params):
        """Deferred/prefetched param all-gather (overlap): rebuild the
        full embed/blocks/head trees from the [1, size/dp] local shard
        views at the top of the step, group by group, chaining gather g
        behind gather g-prefetch_depth via optimization_barrier so at
        most `prefetch_depth` full groups are in flight beyond the
        shards. Passthrough when overlap is off."""
        if not getattr(self, '_pp_overlap', False):
            return params
        layout = self._pp_layout
        gathered = B.gather_groups(
            [sh[0] for sh in params['_shards']], ('dp',), self.dp,
            comm_dtype=self.comm_dtype, block=self._comm_block,
            chunk=self._comm_chunk, prefetch=self._prefetch_depth)
        out = {grp: dict(params[grp])
               for grp in ('embed', 'blocks', 'head')}
        for k, v in layout.unflatten(gathered).items():
            grp, n = k.split('/', 1)
            out[grp][n] = v
        out['_shards'] = params['_shards']
        return out

    def _place(self, arr, spec):
        # copy before placing: device_put to a (partially) replicated
        # sharding can alias the source buffer, and the jitted step DONATES
        # these arrays — aliasing would free the model's eager params.
        return jax.device_put(jnp.array(arr, copy=True),
                              NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------------
    def _block_apply(self, template, param_slice, x, key):
        """Run one decoder block with bound params."""
        with bind_arrays(template, param_slice):
            with rng_mod.rng_guard(key), autograd.no_grad():
                out = template(Tensor(x))
        return out.data

    def _build(self):
        if self.schedule == '1F1B':
            return self._build_1f1b()
        if self.schedule == 'interleaved':
            return self._build_interleaved()
        return self._build_fthenb()

    # -- shared tail of both schedules ---------------------------------------
    def _make_stage_forward(self, save_dots=False):
        """(block_params_local, x, key) -> x: scan this stage's blocks.

        save_dots: instead of full per-block rematerialization, checkpoint
        with a save-MXU-outputs policy — the backward recomputes only the
        cheap elementwise tail (layernorm/gelu/softmax), not the matmuls.
        Used by the activation-stashing 1F1B, whose O(pp) in-flight window
        makes the bigger residual set affordable (the reference
        SectionWorker likewise stores, not recomputes)."""
        block_apply = functools.partial(self._block_apply, self.blocks[0])
        from ..utils.recompute import apply_policy as _apply_remat
        if self._remat_policy is not None:
            # tuned policy (docs/performance.md#remat-policy) replaces
            # the legacy schedule-specific split below
            block_apply = _apply_remat(
                block_apply, self._remat_policy, engine='pipeline')
        elif self.use_remat:
            if save_dots:
                block_apply = _apply_remat(
                    block_apply, 'dots', engine='pipeline')
            else:
                block_apply = _apply_remat(
                    block_apply, 'full', engine='pipeline')

        def stage_forward(block_params_local, x, key):
            def body(carry, xs):
                pslice, k = xs
                return block_apply(pslice, carry, k), None
            n_local = jax.tree_util.tree_leaves(
                block_params_local)[0].shape[0]
            keys = jax.random.split(key, n_local)
            out, _ = lax.scan(body, x, (block_params_local, keys))
            return out
        return stage_forward

    def _reduce_and_update(self, params, states, loss, grads, lr, dp_on,
                           scale=None):
        """Cross-axis loss/grad reductions + optimizer update (both
        schedules): tied/replicated trees (embed, head) psum over pp;
        everything pmeans over dp. With loss scaling, grads unscale here
        and a non-finite gradient anywhere skips the whole update
        (parity: check_finite_and_unscale + update_loss_scaling driven by
        hybrid_parallel_gradscaler.py — found_inf is global after the
        psum/pmean sync, since an inf on any rank infects the reduced
        value)."""
        if getattr(self, '_pp_bucketed', False):
            return self._bucketed_reduce_and_update(
                params, states, loss, grads, lr, dp_on, scale=scale)
        pp = self.pp
        if pp > 1:
            loss = lax.psum(loss, 'pp')  # only last stage ≠ 0
        if dp_on:
            loss = lax.pmean(loss, 'dp')

        def sync(tree, over_pp):
            def one(g):
                if over_pp and pp > 1:
                    g = lax.psum(g, 'pp')
                if dp_on:
                    g = lax.pmean(g, 'dp')
                return g
            return jax.tree_util.tree_map(one, tree)

        grads = {'embed': sync(grads['embed'], True),
                 'blocks': sync(grads['blocks'], False),
                 'head': sync(grads['head'], True)}

        # trace-time telemetry: grad-sync payload per compiled step (the
        # executable replays these psums/pmeans every step)
        if pp > 1 or dp_on:
            from ....core.monitor import counter
            nbytes = sum(
                int(np.prod(g.shape or (1,))) * jnp.dtype(g.dtype).itemsize
                for g in jax.tree_util.tree_leaves(grads))
            counter('ptpu_collective_bytes_total',
                    help='payload bytes through collective APIs',
                    labelnames=('op',)).inc(nbytes, op='pipeline_grad_sync')
            counter('ptpu_collective_calls_total',
                    help='collective API invocations',
                    labelnames=('op',)).inc(1, op='pipeline_grad_sync')

        found_inf = jnp.asarray(False)
        if scale is not None:
            leaves = jax.tree_util.tree_leaves(grads)
            found_inf = jnp.any(jnp.stack(
                [jnp.any(~jnp.isfinite(g)) for g in leaves]))
            # block grads are stage-LOCAL (never psum'd over pp): an
            # overflow on one stage must skip the update on ALL stages or
            # the replicated embed/head trees desync — reduce the flag
            # over pp (dp grads are already pmean'd, so dp ranks agree)
            if pp > 1:
                found_inf = lax.pmax(found_inf.astype(jnp.int32),
                                     'pp') > 0
            inv = (1.0 / scale).astype(jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                grads)

        # numerics taps: post-unscale, pre-update grad stats + the
        # global grad-norm^2. Block grads are stage-LOCAL (never psum'd
        # over pp) so their sum-of-squares reduces over 'pp'; embed/head
        # are already fully reduced. Per-tensor stats for blocks cover
        # the local stage's slice under pp>1 (the global norm is exact).
        taps_on = getattr(self, '_taps_on', False)
        flat_grads = gn_sq = None
        if taps_on:
            sq_eh = jnp.asarray(0.0, jnp.float32)
            for grp in ('embed', 'head'):
                for g in grads[grp].values():
                    sq_eh = sq_eh + jnp.sum(g.astype(jnp.float32) ** 2)
            sq_b = jnp.asarray(0.0, jnp.float32)
            for g in grads['blocks'].values():
                sq_b = sq_b + jnp.sum(g.astype(jnp.float32) ** 2)
            if pp > 1:
                sq_b = lax.psum(sq_b, 'pp')
            gn_sq = sq_eh + sq_b
            flat_grads = {f'{grp}/{n}': g
                          for grp in ('embed', 'blocks', 'head')
                          for n, g in grads[grp].items()}

        new_params, new_states = {}, {'_buckets': []}
        for grp in ('embed', 'blocks', 'head'):
            new_params[grp], new_states[grp] = {}, {}
            for n, p in params[grp].items():
                np_, ns = self._update_one(
                    p, grads[grp][n], dict(states[grp][n]), lr)
                if scale is not None:
                    np_ = jnp.where(found_inf, p, np_)
                    ns = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(found_inf, old, new),
                        ns, dict(states[grp][n]))
                new_params[grp][n] = np_
                new_states[grp][n] = ns
        if taps_on:
            from ....core import numerics as _num
            flat_params = {f'{grp}/{n}': p
                           for grp in ('embed', 'blocks', 'head')
                           for n, p in new_params[grp].items()}
            taps = _num.jit_taps(flat_grads, flat_params,
                                 extra_norm_sq=gn_sq)
            return loss, new_params, new_states, found_inf, taps
        return loss, new_params, new_states, found_inf

    def _bucketed_reduce_and_update(self, params, states, loss, grads, lr,
                                    dp_on, scale=None):
        """Bucketed twin of `_reduce_and_update` (arXiv:2004.13336):
        embed/head grads still psum over 'pp' (tied-weight sync), then
        every eligible grad coalesces into flat buckets, each bucket
        moves through ONE reduce_scatter over 'dp' (compressed wire
        under `comm_dtype`), this rank updates its 1/dp shard of params
        + optimizer moments, and ONE all_gather per bucket rebuilds the
        updated params. mp-sharded params fall back to the per-param
        path; a nonfinite gradient anywhere still skips the whole
        update (found_inf pmax over dp and pp — shards differ per dp
        rank, so the dp reduction is load-bearing here)."""
        pp = self.pp
        layout = self._pp_layout
        if pp > 1:
            loss = lax.psum(loss, 'pp')  # only last stage ≠ 0
        if dp_on:
            loss = lax.pmean(loss, 'dp')

        def pp_sync(tree):
            if pp > 1:
                return jax.tree_util.tree_map(
                    lambda g: lax.psum(g, 'pp'), tree)
            return tree

        grads = {'embed': pp_sync(grads['embed']),
                 'blocks': grads['blocks'],
                 'head': pp_sync(grads['head'])}
        flat_named = {f'{grp}/{n}': g
                      for grp in ('embed', 'blocks', 'head')
                      for n, g in grads[grp].items()}
        accum_fp32 = self.grad_accum_dtype != 'param'
        legacy = {k: v for k, v in flat_named.items()
                  if k not in layout.slots}
        if dp_on:
            legacy = {k: lax.pmean(v, 'dp') for k, v in legacy.items()}
        flat_grads = layout.flatten(
            {k: flat_named[k] for k in layout.slots},
            cast=jnp.float32 if accum_fp32 else None)
        shards32 = [B.reduce_scatter(f, ('dp',), self.dp,
                                     comm_dtype=self.comm_dtype,
                                     mean=True,
                                     block=self._comm_block,
                                     chunk=self._comm_chunk)
                    for f in flat_grads]

        # trace-time telemetry: rs+ag wire bytes (scales + padding
        # included) replayed every step
        from ....core.monitor import counter
        wires = B.wire_bytes(layout, max(self.dp, 1),
                             self.comm_dtype or (
                                 jnp.float32 if accum_fp32 else None),
                             self._comm_block)
        nbytes = (wires['reduce_scatter']['total']
                  + wires['all_gather']['total'])
        counter('ptpu_collective_bytes_total',
                help='payload bytes through collective APIs',
                labelnames=('op',)).inc(nbytes, op='pipeline_bucket_rs_ag')
        counter('ptpu_collective_calls_total',
                help='collective API invocations',
                labelnames=('op',)).inc(2 * len(layout.buckets),
                                        op='pipeline_bucket_rs_ag')

        found_inf = jnp.asarray(False)
        inv = None
        fi_guard = None
        if scale is not None:
            # per-bucket found-inf from the same one-pass stats kernel
            # the fused optimizer step uses (nonfinite COUNT > 0 ==
            # any(~isfinite)); legacy params keep the per-param check
            flags = [B.grad_stats(g)[1] > 0 for g in shards32]
            flags += [jnp.any(~jnp.isfinite(v)) for v in legacy.values()]
            f = (jnp.any(jnp.stack(flags)) if flags
                 else jnp.asarray(False)).astype(jnp.int32)
            if dp_on:
                f = lax.pmax(f, 'dp')
            if pp > 1:
                f = lax.pmax(f, 'pp')
            found_inf = f > 0
            fi_guard = found_inf
            inv = (1.0 / scale).astype(jnp.float32)
            legacy = {k: (v.astype(jnp.float32) * inv).astype(v.dtype)
                      for k, v in legacy.items()}

        # numerics taps (diagnostics mode): the hot path never
        # materializes fully-reduced per-param grads, so pay one extra
        # pmean per param to surface them — observation only, the
        # update below still consumes the bucket shards
        taps_on = getattr(self, '_taps_on', False)
        tap_grads = gn_sq = None
        if taps_on:
            tap_grads = {}
            for k in layout.slots:
                g = flat_named[k]
                g = lax.pmean(g, 'dp') if dp_on else g
                if inv is not None:
                    g = (g.astype(jnp.float32) * inv).astype(g.dtype)
                tap_grads[k] = g
            tap_grads.update(legacy)
            sq_eh = jnp.asarray(0.0, jnp.float32)
            sq_b = jnp.asarray(0.0, jnp.float32)
            for k, g in tap_grads.items():
                v = jnp.sum(g.astype(jnp.float32) ** 2)
                if k.startswith('blocks/'):
                    sq_b = sq_b + v
                else:
                    sq_eh = sq_eh + v
            if pp > 1:
                sq_b = lax.psum(sq_b, 'pp')
            gn_sq = sq_eh + sq_b

        overlap = getattr(self, '_pp_overlap', False)
        if not overlap:
            slot_params = {k: params[k.split('/', 1)[0]]
                           [k.split('/', 1)[1]]
                           for k in layout.slots}
            flat_params = layout.flatten(slot_params)
        new_flat, new_shards, new_buckets = [], [], []
        for gi, (b, g32, st_in) in enumerate(
                zip(layout.buckets, shards32, states['_buckets'])):
            # local vector-state view is [1, shard]: drop/restore the
            # leading pp dim around the flat update
            st = {k: (v[0] if getattr(v, 'ndim', 0) >= 2 else v)
                  for k, v in st_in.items()}
            # overlap: this rank's stored param shard IS the slice
            # take_shard would cut out of the materialized replica
            p_shard = params['_shards'][gi][0] if overlap else \
                B.take_shard(flat_params[gi], ('dp',), self.dp)
            # unscale multiply + found-inf no-op guard fold into the
            # one-pass fused update (prefactor/found_inf); the
            # reference route applies the same ops in the same order
            np_, ns = B.shard_update(self.optimizer, p_shard, g32, st,
                                     lr, prefactor=inv,
                                     found_inf=fi_guard)
            new_buckets.append(
                {k: (v[None] if getattr(v, 'ndim', 0) >= 1 else v)
                 for k, v in ns.items()})
            if overlap:
                # deferred gather: the updated shard is the engine
                # state; its all-gather runs at the NEXT step's top,
                # under that step's early forward compute
                new_shards.append(np_[None])
            else:
                new_flat.append(B.all_gather(np_, ('dp',),
                                             comm_dtype=self.comm_dtype,
                                             block=self._comm_block,
                                             chunk=self._comm_chunk,
                                             n_shards=self.dp))

        new_params = {'embed': {}, 'blocks': {}, 'head': {}}
        new_states = {'embed': {}, 'blocks': {}, 'head': {},
                      '_buckets': new_buckets}
        if overlap:
            new_params['_shards'] = new_shards
        else:
            for k, v in layout.unflatten(new_flat).items():
                grp, n = k.split('/', 1)
                new_params[grp][n] = v
        for k, g in legacy.items():
            grp, n = k.split('/', 1)
            p = params[grp][n]
            old = dict(states[grp][n])
            np_, ns = self._update_one(p, g, dict(old), lr)
            if scale is not None:
                np_ = jnp.where(found_inf, p, np_)
                ns = jax.tree_util.tree_map(
                    lambda new, old_: jnp.where(found_inf, old_, new),
                    ns, old)
            new_params[grp][n] = np_
            new_states[grp][n] = ns

        if taps_on:
            from ....core import numerics as _num
            flat_params_tap = {f'{grp}/{n}': p
                               for grp in ('embed', 'blocks', 'head')
                               for n, p in new_params[grp].items()}
            if overlap:
                # diagnostics mode pays the gather the hot path
                # deferred, so per-param stats see full params
                flat_params_tap.update(layout.unflatten(
                    B.gather_groups([s2[0] for s2 in new_shards],
                                    ('dp',), self.dp,
                                    comm_dtype=self.comm_dtype,
                                    block=self._comm_block,
                                    chunk=self._comm_chunk)))
            taps = _num.jit_taps(tap_grads, flat_params_tap,
                                 extra_norm_sq=gn_sq)
            return loss, new_params, new_states, found_inf, taps
        return loss, new_params, new_states, found_inf

    def _finalize(self, step, dp_on):
        # on-device LR schedule: the lr slot carries a device int32
        # step counter; the compiled step derives lr = fn(counter) and
        # returns counter+1 (no per-step host LR compute or H2D feed)
        lr_fn = self._lr.fn
        if lr_fn is not None:
            base_step = step

            def step(params, states, step_c, scale, key, ii, ll):
                out = base_step(params, states,
                                lr_fn(step_c).astype(jnp.float32),
                                scale, key, ii, ll)
                return out[:4] + (step_c + 1,) + out[4:]

        dp_sp = P('dp') if dp_on else P()
        in_specs = (self._specs, self._state_specs, P(), P(), P(), dp_sp,
                    dp_sp)
        out_specs = (P(), self._specs, self._state_specs, P())
        if lr_fn is not None:
            out_specs = out_specs + (P(),)
        if getattr(self, '_taps_on', False):
            from ....core import numerics as _num
            # ALL trainable params (overlap mode keeps bucketed slots
            # out of the group trees, but taps still cover them)
            keys = [f'embed/{n}' for n, _ in self._embed_named] \
                + [f'blocks/{n}' for n, _ in self._block_named] \
                + [f'head/{n}' for n, _ in self._head_named]
            out_specs = out_specs + (_num.taps_spec(
                {'grads': dict.fromkeys(keys, 0),
                 'params': dict.fromkeys(keys, 0),
                 'grad_norm_sq': 0}),)
        mapped = shard_map(step, mesh=self.mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
        return jax.jit(mapped, donate_argnums=(0, 1))

    @staticmethod
    def _split_residuals(fn, args, variant_argnums):
        """Taint-split the flattened outputs of ``fn(*args)`` into
        tick-VARIANT ones (those depending on the arguments named in
        ``variant_argnums``) and tick-INVARIANT ones, and evaluate the
        invariant ones once by running only their pruned sub-graph (weight
        casts/transposes — never the stage forward).

        The taint walk is a conservative jaxpr pass: any eqn with a
        tainted operand taints all its outputs (higher-order primitives
        are treated atomically — sound because scan/cond/pjit consts are
        hoisted to explicit invars in final-style jaxprs). Used to split
        per-microbatch vjp residuals into activation residuals (buffered
        per in-flight microbatch) and weight-derived residuals (computed
        once per step, shared by every tick). An output misclassified as
        variant merely wastes buffer space; it can never produce a wrong
        gradient.

        Returns ``(variant_flags, values, avals)``: ``values[i]`` holds
        the invariant output value (None at variant positions); ``avals``
        are every flattened output's abstract values, so callers need no
        second abstract trace for shapes."""
        closed = jax.make_jaxpr(fn)(*args)
        jaxpr = closed.jaxpr
        variant_flat = []
        for i, a in enumerate(args):
            n = len(jax.tree_util.tree_leaves(a))
            variant_flat += [i in variant_argnums] * n
        tainted = set()
        for var, isv in zip(jaxpr.invars, variant_flat):
            if isv:
                tainted.add(var)

        def _is_tainted(v):
            return not hasattr(v, 'val') and v in tainted  # Literal: .val

        for eqn in jaxpr.eqns:
            if any(_is_tainted(v) for v in eqn.invars):
                tainted.update(eqn.outvars)
        flags = [_is_tainted(v) for v in jaxpr.outvars]

        # dead-code-eliminate from the invariant outputs, then evaluate
        # just that sub-graph (it never touches a variant input, so this
        # runs no microbatch compute)
        want = [v for v, f in zip(jaxpr.outvars, flags) if not f]
        needed = {v for v in want if not hasattr(v, 'val')}
        keep = []
        for eqn in reversed(jaxpr.eqns):
            if any(o in needed for o in eqn.outvars):
                keep.append(eqn)
                needed.update(v for v in eqn.invars
                              if not hasattr(v, 'val'))
        keep.reverse()
        try:     # jax>=0.4.36 asserts debug_info paths match outvars
            pruned = jaxpr.replace(eqns=keep, outvars=want,
                                   debug_info=None)
        except (TypeError, AssertionError):
            pruned = jaxpr.replace(eqns=keep, outvars=want)
        flat_args = jax.tree_util.tree_leaves(args)
        inv_vals = jax.core.eval_jaxpr(pruned, closed.consts, *flat_args)
        values = [None] * len(flags)
        it = iter(inv_vals)
        for i, f in enumerate(flags):
            if not f:
                values[i] = next(it)
        avals = [v.aval if not hasattr(v, 'val')
                 else jax.core.get_aval(v.val)
                 for v in jaxpr.outvars]
        return flags, values, avals

    def _build_1f1b(self):
        """1F1B steady-state schedule (section_worker.cc:147-184 parity).

        TPU-native formulation: ONE `lax.scan` over T = A + 2*(pp-1) ticks.
        Every tick, every stage runs one forward sub-step (microbatch
        m_f = t - stage) and one backward sub-step (microbatch
        m_b = t - (2*(pp-1) - stage)), lockstep-SPMD with `jnp.where`
        masking outside the active windows. Activations flow +1 over the
        'pp' ring and cotangents flow -1, one `lax.ppermute` each per tick.

        Memory/compute, per ``memory_mode``:
          * 'stash' (default — the reference SectionWorker's
            store-activations 1F1B): the forward sub-step runs under
            `jax.vjp`, and the pullback — a `jax.tree_util.Partial`, i.e.
            a real pytree of residual arrays — is flattened; the
            tick-VARIANT residual leaves (activations; identified by
            `_split_residuals`) go into a circular buffer of
            B = min(A, 2*pp-1) slots, while weight-derived leaves are
            taken from the current tick's forward call (tick-invariant,
            so bit-identical). The backward sub-step unflattens the
            pullback from the buffered slot and applies it — the stage
            forward is never re-run. Stage FLOPs: fwd + bwd.
          * 'recompute': only the stage-INPUT activation of each
            in-flight microbatch is buffered; backward re-runs the stage
            from the saved input via a local `jax.vjp` consumed in the
            same tick (full-remat cost). Lower memory, +1 fwd FLOPs.
        Either way live state is O(pp), not O(A) — the reference 1F1B's
        memory property (in-flight <= 2*(pp-1)+1 here vs Megatron's pp:
        the constant-factor price of every stage doing fwd+bwd each tick
        in lockstep). Stage 0 embeds each microbatch on its tick — no
        [A, mb, L, H] up-front buffer.
        """
        A, pp = self.A, self.pp
        axes = self.axes
        embed, head = self.embed, self.head
        opt = self.optimizer
        dp_on = 'dp' in axes and self.mesh.shape['dp'] > 1
        use_scaling = self._use_scaling
        stash = self.memory_mode == 'stash'
        B = min(A, 2 * pp - 1)
        T = A + 2 * (pp - 1)
        # pp=1: backward always consumes the SAME tick's forward (m_b ==
        # m_f), so nothing crosses ticks — no residual buffering, and full
        # per-block remat stays the memory-safe choice for the single-chip
        # memory-bound configs (the save-dots residual set there would
        # cover the WHOLE model, not one stage)
        save_dots = stash and pp > 1
        stage_forward = self._make_stage_forward(save_dots=save_dots)

        def step(params, states, lr, scale, key, input_ids, labels):
            with C.spmd_region(axes):
                params = self._materialize_params(params)
                stage = lax.axis_index('pp') if pp > 1 else 0
                is_last = stage == pp - 1
                mb = input_ids.shape[0] // A
                pe, pb, ph = params['embed'], params['blocks'], params['head']
                k0 = key
                if dp_on:
                    k0 = jax.random.fold_in(k0, lax.axis_index('dp'))

                ids_mb = input_ids.reshape(A, mb, *input_ids.shape[1:])
                labels_mb = labels.reshape(A, mb, *labels.shape[1:])

                def embed_apply(pe_, ids_m, k):
                    with bind_arrays(embed, pe_):
                        with rng_mod.rng_guard(k), autograd.no_grad():
                            return embed(Tensor(ids_m)).data

                def head_apply(ph_, out, lab, k):
                    with bind_arrays(head, ph_):
                        with rng_mod.rng_guard(k), autograd.no_grad():
                            return head(Tensor(out), Tensor(lab)).data \
                                .astype(jnp.float32)

                emb_shape = jax.eval_shape(
                    embed_apply, pe, ids_mb[0], k0)
                act_shape, act_dtype = emb_shape.shape, emb_shape.dtype

                def fwd_only(pe_, pb_, x_in, m, k_mb):
                    """Forward sub-step: embed (stage 0) + local blocks.
                    Keys derive from (microbatch, stage) so the backward
                    recompute replays identical dropout."""
                    ke = jax.random.fold_in(k_mb, 17)
                    ks = jax.random.fold_in(
                        jax.random.fold_in(k_mb, 31), stage)
                    if pp > 1:
                        x = lax.cond(
                            stage == 0,
                            lambda: embed_apply(pe_, ids_mb[m], ke),
                            lambda: x_in)
                    else:
                        x = embed_apply(pe_, ids_mb[m], ke)
                    return stage_forward(pb_, x, ks)

                def full_fn(p3, x_in, m, k_mb):
                    """fwd_only + head loss (last stage) — the function the
                    backward sub-step differentiates."""
                    pe_, pb_, ph_ = p3
                    out = fwd_only(pe_, pb_, x_in, m, k_mb)
                    kh = jax.random.fold_in(k_mb, 7919)
                    if pp > 1:
                        loss = lax.cond(
                            is_last,
                            lambda: head_apply(ph_, out, labels_mb[m], kh),
                            lambda: jnp.asarray(0.0, jnp.float32))
                    else:
                        loss = head_apply(ph_, out, labels_mb[m], kh)
                    return out, loss

                acc_param = self.grad_accum_dtype == 'param'
                gacc0 = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(
                        a.shape, a.dtype if acc_param else jnp.float32),
                    (pe, pb, ph))

                def grad_cot():
                    return (scale / A).astype(jnp.float32) \
                        if use_scaling else jnp.asarray(1.0 / A,
                                                        jnp.float32)

                def accum(gacc, d_p3, b_active):
                    return jax.tree_util.tree_map(
                        lambda a, g: a + jnp.where(
                            b_active, g.astype(a.dtype),
                            jnp.zeros((), a.dtype)),
                        gacc, d_p3)

                if stash:
                    # -- activation-stashing 1F1B ------------------------
                    box = {}

                    def fwd_probe(p3, x_in, m, k_mb):
                        (out, loss), vjp_fn = jax.vjp(
                            lambda p, xx: full_fn(p, xx, m, k_mb),
                            p3, x_in)
                        leaves, treedef = jax.tree_util.tree_flatten(vjp_fn)
                        box['treedef'] = treedef
                        return out, loss, leaves

                    probe_args = ((pe, pb, ph),
                                  jnp.zeros(act_shape, act_dtype),
                                  jnp.asarray(0, jnp.int32), k0)
                    flags, inv_vals, avals = self._split_residuals(
                        fwd_probe, probe_args, {1, 2, 3})
                    leaf_shapes = avals[2:]
                    leaf_var = flags[2:]
                    inv_leaves = inv_vals[2:]
                    var_idx = [i for i, v in enumerate(leaf_var) if v]
                    # B real slots + 1 scratch slot: inactive forward ticks
                    # write to the scratch slot, so the hot path is a pure
                    # dynamic-update (no read-old + select per leaf, which
                    # would force XLA to materialize a buffer copy per tick
                    # instead of updating the loop carry in place).
                    # pp=1: same-tick consumption — no buffers at all.
                    bufs0 = tuple(
                        jnp.zeros((B + 1,) + tuple(leaf_shapes[i].shape),
                                  leaf_shapes[i].dtype)
                        for i in var_idx) if pp > 1 else ()
                    carry0 = (jnp.zeros(act_shape, act_dtype),  # fwd act
                              jnp.zeros(act_shape, act_dtype),  # cotangent
                              bufs0,                            # residuals
                              gacc0,
                              jnp.asarray(0.0, jnp.float32))    # loss acc

                    def tick(carry, t):
                        fwd_act, grad_in, bufs, gacc, loss_acc = carry

                        m_f = t - stage
                        f_active = (m_f >= 0) & (m_f < A)
                        m_fc = jnp.clip(m_f, 0, A - 1)
                        m_b = t - (2 * (pp - 1) - stage)
                        b_active = (m_b >= 0) & (m_b < A)
                        m_bc = jnp.clip(m_b, 0, A - 1)
                        slot_b = jnp.mod(m_bc, B)

                        # -- forward sub-step: microbatch m_f = t - stage;
                        # runs under vjp so its pullback's residuals
                        # exist. Gated on the tick range in which ANY
                        # stage still forwards — the predicate is uniform
                        # across the mesh, so the cond's mp collectives
                        # see uniform control flow and the bwd-only drain
                        # ticks pay no forward at all (total work = A+pp-1
                        # forwards + A+pp-1 backwards, same as F-then-B).
                        def do_fwd():
                            out, l_f, leaves = fwd_probe(
                                (pe, pb, ph), fwd_act, m_fc,
                                jax.random.fold_in(k0, m_fc))
                            return (out, l_f,
                                    [leaves[i] for i in var_idx])

                        def skip_fwd():
                            return (jnp.zeros(act_shape, act_dtype),
                                    jnp.asarray(0.0, jnp.float32),
                                    [jnp.zeros(tuple(leaf_shapes[i].shape),
                                               leaf_shapes[i].dtype)
                                     for i in var_idx])

                        out_f, loss_f, vleaves = lax.cond(
                            t < A + pp - 1, do_fwd, skip_fwd)
                        slot_f = jnp.where(f_active, jnp.mod(m_fc, B), B)
                        bufs = tuple(
                            lax.dynamic_update_index_in_dim(
                                buf, vl, slot_f, 0)
                            for buf, vl in zip(bufs, vleaves))
                        loss_acc = loss_acc + jnp.where(f_active, loss_f,
                                                        0.0)

                        # Reading after the write is correct: the only
                        # same-tick producer-consumer is the last stage
                        # (m_b == m_f), where the just-written slot is
                        # exactly the wanted fresh data; inactive
                        # forwards write the scratch slot so they can
                        # never clobber a pending slot. pp=1 is ALL
                        # same-tick: take the fresh leaves directly.
                        gathered = vleaves if pp == 1 else [
                            lax.dynamic_index_in_dim(
                                buf, slot_b, 0, keepdims=False)
                            for buf in bufs]

                        # -- backward sub-step: m_b = t-(2(pp-1)-stage);
                        # pullback rebuilt from the stashed residuals —
                        # the stage forward is NOT re-run. Gated on the
                        # warm-up ticks where no stage has a backward yet.
                        def do_bwd():
                            leaves_b = list(inv_leaves)
                            for g, i in zip(gathered, var_idx):
                                leaves_b[i] = g
                            vjp_b = jax.tree_util.tree_unflatten(
                                box['treedef'], leaves_b)
                            g_out = jnp.where(
                                is_last,
                                jnp.zeros(act_shape, act_dtype),
                                grad_in.astype(act_dtype))
                            return vjp_b((g_out, grad_cot()))

                        def skip_bwd():
                            return (jax.tree_util.tree_map(
                                jnp.zeros_like, (pe, pb, ph)),
                                jnp.zeros(act_shape, act_dtype))

                        d_p3, dx = lax.cond(t >= pp - 1, do_bwd, skip_bwd)
                        gacc = accum(gacc, d_p3, b_active)
                        dx = jnp.where(b_active, dx, jnp.zeros_like(dx))

                        if pp > 1:
                            nxt_act = lax.ppermute(
                                out_f, 'pp',
                                [(i, (i + 1) % pp) for i in range(pp)])
                            nxt_grad = lax.ppermute(
                                dx, 'pp',
                                [(i, (i - 1) % pp) for i in range(pp)])
                        else:
                            nxt_act, nxt_grad = out_f, dx
                        return (nxt_act, nxt_grad, bufs, gacc,
                                loss_acc), None
                else:
                    # -- recompute 1F1B (stage-input buffer only) --------
                    carry0 = (jnp.zeros(act_shape, act_dtype),  # fwd act
                              jnp.zeros(act_shape, act_dtype),  # cotangent
                              jnp.zeros((B + 1,) + act_shape,
                                        act_dtype),             # inputs buf
                              gacc0,
                              jnp.asarray(0.0, jnp.float32))    # loss acc

                    def tick(carry, t):
                        fwd_act, grad_in, buf, gacc, loss_acc = carry

                        m_f = t - stage
                        f_active = (m_f >= 0) & (m_f < A)
                        m_fc = jnp.clip(m_f, 0, A - 1)
                        m_b = t - (2 * (pp - 1) - stage)
                        b_active = (m_b >= 0) & (m_b < A)
                        m_bc = jnp.clip(m_b, 0, A - 1)
                        # read-before-write (see stash tick) + same-tick
                        # select for the last stage
                        x_read = lax.dynamic_index_in_dim(
                            buf, jnp.mod(m_bc, B), 0, keepdims=False)
                        p_same = jnp.logical_and(m_fc == m_bc, f_active)
                        x_saved = jnp.where(p_same, fwd_act, x_read)

                        # -- forward sub-step: microbatch m_f = t - stage
                        out_f = fwd_only(pe, pb, fwd_act, m_fc,
                                         jax.random.fold_in(k0, m_fc))
                        # stash this microbatch's stage input (scratch slot
                        # B absorbs inactive ticks — pure in-place update)
                        slot_f = jnp.where(f_active, jnp.mod(m_fc, B), B)
                        buf = lax.dynamic_update_index_in_dim(
                            buf, fwd_act, slot_f, 0)

                        # -- backward sub-step: m_b = t-(2(pp-1)-stage) --
                        k_b = jax.random.fold_in(k0, m_bc)
                        (_out_p, loss_p), vjp_fn = jax.vjp(
                            lambda p3, x: full_fn(p3, x, m_bc, k_b),
                            (pe, pb, ph), x_saved)
                        g_out = jnp.where(is_last, jnp.zeros_like(_out_p),
                                          grad_in.astype(_out_p.dtype))
                        d_p3, dx = vjp_fn((g_out, grad_cot()))
                        gacc = accum(gacc, d_p3, b_active)
                        loss_acc = loss_acc + jnp.where(b_active, loss_p,
                                                        0.0)
                        dx = jnp.where(b_active, dx, jnp.zeros_like(dx))

                        if pp > 1:
                            nxt_act = lax.ppermute(
                                out_f, 'pp',
                                [(i, (i + 1) % pp) for i in range(pp)])
                            nxt_grad = lax.ppermute(
                                dx, 'pp',
                                [(i, (i - 1) % pp) for i in range(pp)])
                        else:
                            nxt_act, nxt_grad = out_f, dx
                        return (nxt_act, nxt_grad, buf, gacc,
                                loss_acc), None

                (_, _, _, gacc, loss_sum), _ = lax.scan(
                    tick, carry0, jnp.arange(T))
                grads = {'embed': gacc[0], 'blocks': gacc[1],
                         'head': gacc[2]}
                return self._reduce_and_update(
                    params, states, loss_sum / A, grads, lr, dp_on,
                    scale=scale if use_scaling else None)

        return self._finalize(step, dp_on)

    def _build_interleaved(self):
        """Interleaved virtual-stage 1F1B (arXiv:2104.04473; Megatron's
        num_model_chunks schedule).

        Each physical stage holds v model chunks; global virtual stage
        g = c*pp + s runs chunk c on device s. ONE `lax.scan` over
        T = A*v + D ticks, D = 2*(pp-1) + (v-1)*pp: every tick each
        device runs ONE chunk-forward (its job stream index
        j_f = t - stage; job j -> chunk c = (j mod pp*v) // pp,
        microbatch m = (j // (pp*v))*pp + j mod pp — microbatches
        advance in groups of pp per chunk, hence A % pp == 0) and ONE
        chunk-backward (j_b = t - (D - stage); reversed chunk order
        within each group). Activations still move +1 and cotangents
        -1 over the SAME 'pp' ring, one `lax.ppermute` each per tick:
        the ring wrap pp-1 -> 0 carries a microbatch from chunk c-1
        into chunk c, so boundary crossings scale ~v x while every
        masked warm-up/drain tick now burns 1/v of a stage — the
        modeled bubble shrinks from (pp-1)/(A+pp-1) to
        (pp-1)/(A*v+pp-1) (see schedule_model).

        The O(pp) residual machinery generalizes to per-(chunk,
        in-flight-microbatch) slots: `memory_mode='stash'` buffers the
        tick-variant vjp residual leaves in slots_per_chunk slots per
        chunk (weight-derived leaves are evaluated once per chunk and
        selected by c_b inside the scan); 'recompute' buffers only the
        chunk-input activation per slot. Tied/replicated grads keep
        their pp-psum semantics unchanged (_reduce_and_update).
        v == 1 degenerates to the classic 1F1B tick table."""
        A, pp, v = self.A, self.pp, self.vp
        axes = self.axes
        embed, head = self.embed, self.head
        dp_on = 'dp' in axes and self.mesh.shape['dp'] > 1
        use_scaling = self._use_scaling
        stash = self.memory_mode == 'stash'
        ppv = pp * v
        D = 2 * (pp - 1) + (v - 1) * pp
        T = A * v + D
        K = min(self._sched_model['slots_per_chunk'], A)
        nslots = v * K
        per = len(self.blocks) // ppv       # layers per chunk
        # pp*v == 1: every backward consumes the same tick's forward —
        # full per-block remat stays the memory-safe single-chip choice
        # (the v=1 1F1B rationale)
        save_dots = stash and ppv > 1
        stage_forward = self._make_stage_forward(save_dots=save_dots)

        def step(params, states, lr, scale, key, input_ids, labels):
            with C.spmd_region(axes):
                params = self._materialize_params(params)
                stage = lax.axis_index('pp') if pp > 1 else 0
                mb = input_ids.shape[0] // A
                pe, pb, ph = params['embed'], params['blocks'], params['head']
                k0 = key
                if dp_on:
                    k0 = jax.random.fold_in(k0, lax.axis_index('dp'))

                ids_mb = input_ids.reshape(A, mb, *input_ids.shape[1:])
                labels_mb = labels.reshape(A, mb, *labels.shape[1:])

                def embed_apply(pe_, ids_m, k):
                    with bind_arrays(embed, pe_):
                        with rng_mod.rng_guard(k), autograd.no_grad():
                            return embed(Tensor(ids_m)).data

                def head_apply(ph_, out, lab, k):
                    with bind_arrays(head, ph_):
                        with rng_mod.rng_guard(k), autograd.no_grad():
                            return head(Tensor(out), Tensor(lab)).data \
                                .astype(jnp.float32)

                emb_shape = jax.eval_shape(
                    embed_apply, pe, ids_mb[0], k0)
                act_shape, act_dtype = emb_shape.shape, emb_shape.dtype

                def chunk_slice(tree, c):
                    """This device's rows for chunk c: local leaves are
                    [v*per, ...] chunk-major (chunk_layer_order)."""
                    return jax.tree_util.tree_map(
                        lambda l: lax.dynamic_slice_in_dim(
                            l, c * per, per, 0), tree)

                def fwd_only(pe_, pbc_, x_in, m, c, k_mb):
                    """One chunk-forward: embed feeds virtual stage 0
                    (device 0, chunk 0); everyone else consumes the
                    ring. Keys derive from (microbatch, GLOBAL virtual
                    stage) — identical to the v=1 keys when v == 1."""
                    ke = jax.random.fold_in(k_mb, 17)
                    ks = jax.random.fold_in(
                        jax.random.fold_in(k_mb, 31), c * pp + stage)
                    if ppv > 1:
                        x = lax.cond(
                            jnp.logical_and(stage == 0, c == 0),
                            lambda: embed_apply(pe_, ids_mb[m], ke),
                            lambda: x_in)
                    else:
                        x = embed_apply(pe_, ids_mb[m], ke)
                    return stage_forward(pbc_, x, ks)

                def full_fn(p3, x_in, m, c, k_mb):
                    """fwd_only + head loss on the LAST virtual stage
                    (device pp-1, chunk v-1) — what backward
                    differentiates. p3 carries the CHUNK's block rows
                    so the pullback yields chunk-shaped cotangents."""
                    pe_, pbc_, ph_ = p3
                    out = fwd_only(pe_, pbc_, x_in, m, c, k_mb)
                    kh = jax.random.fold_in(k_mb, 7919)
                    if ppv > 1:
                        loss = lax.cond(
                            jnp.logical_and(stage == pp - 1, c == v - 1),
                            lambda: head_apply(ph_, out, labels_mb[m],
                                               kh),
                            lambda: jnp.asarray(0.0, jnp.float32))
                    else:
                        loss = head_apply(ph_, out, labels_mb[m], kh)
                    return out, loss

                acc_param = self.grad_accum_dtype == 'param'
                gacc0 = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(
                        a.shape, a.dtype if acc_param else jnp.float32),
                    (pe, pb, ph))

                def grad_cot():
                    return (scale / A).astype(jnp.float32) \
                        if use_scaling else jnp.asarray(1.0 / A,
                                                        jnp.float32)

                def accum_full(acc, d, active):
                    return jax.tree_util.tree_map(
                        lambda a, g: a + jnp.where(
                            active, g.astype(a.dtype),
                            jnp.zeros((), a.dtype)),
                        acc, d)

                def accum_chunk(acc, d_chunk, c, active):
                    """Add a chunk-shaped block cotangent into rows
                    [c*per, (c+1)*per) of the local accumulator."""
                    def one(a, g):
                        cur = lax.dynamic_slice_in_dim(a, c * per, per, 0)
                        upd = cur + jnp.where(
                            active, g.astype(a.dtype),
                            jnp.zeros((), a.dtype))
                        return lax.dynamic_update_slice_in_dim(
                            a, upd, c * per, 0)
                    return jax.tree_util.tree_map(one, acc, d_chunk)

                def fwd_job(t):
                    """tick -> (active, chunk, microbatch) of this
                    device's forward job stream."""
                    j = t - stage
                    active = (j >= 0) & (j < A * v)
                    jc = jnp.clip(j, 0, A * v - 1)
                    q = jnp.mod(jc, ppv)
                    c = q // pp
                    m = (jc // ppv) * pp + jnp.mod(q, pp)
                    return active, c, m

                def bwd_job(t):
                    """Backward stream: reversed chunk order within
                    each pp-microbatch group."""
                    j = t - (D - stage)
                    active = (j >= 0) & (j < A * v)
                    jc = jnp.clip(j, 0, A * v - 1)
                    q = jnp.mod(jc, ppv)
                    c = (v - 1) - q // pp
                    m = (jc // ppv) * pp + jnp.mod(q, pp)
                    return active, c, m

                if stash:
                    # -- activation-stashing interleaved 1F1B ------------
                    box = {}

                    def fwd_probe(p3, x_in, m, c, k_mb):
                        (out, loss), vjp_fn = jax.vjp(
                            lambda p, xx: full_fn(p, xx, m, c, k_mb),
                            p3, x_in)
                        leaves, treedef = jax.tree_util.tree_flatten(vjp_fn)
                        box['treedef'] = treedef
                        return out, loss, leaves

                    # taint split per chunk: x_in/m/k are tick-variant
                    # (buffered per slot); the chunk id + its weight
                    # rows are per-chunk constants, so the pruned
                    # weight-derived residual graph is evaluated ONCE
                    # per chunk and stacked for in-scan selection
                    flags = avals = None
                    inv_per_c = []
                    for c in range(v):
                        pbc = jax.tree_util.tree_map(
                            lambda l: lax.slice_in_dim(
                                l, c * per, (c + 1) * per, axis=0), pb)
                        probe_args = ((pe, pbc, ph),
                                      jnp.zeros(act_shape, act_dtype),
                                      jnp.asarray(0, jnp.int32),
                                      jnp.asarray(c, jnp.int32), k0)
                        fl, inv_vals, avs = self._split_residuals(
                            fwd_probe, probe_args, {1, 2, 4})
                        if flags is None:
                            flags, avals = fl, avs
                        else:
                            assert fl == flags, \
                                "chunk residual split diverged"
                        inv_per_c.append(inv_vals)
                    leaf_shapes = avals[2:]
                    leaf_var = flags[2:]
                    var_idx = [i for i, f in enumerate(leaf_var) if f]
                    inv_idx = [i for i, f in enumerate(leaf_var)
                               if not f]
                    inv_stacks = [
                        jnp.stack([inv_per_c[c][2 + i]
                                   for c in range(v)])
                        for i in inv_idx]
                    # v*K real slots (chunk-major) + 1 scratch slot for
                    # inactive forwards — the same pure
                    # dynamic-update-in-place trick as v=1
                    bufs0 = tuple(
                        jnp.zeros(
                            (nslots + 1,) + tuple(leaf_shapes[i].shape),
                            leaf_shapes[i].dtype)
                        for i in var_idx)
                    carry0 = (jnp.zeros(act_shape, act_dtype),  # fwd act
                              jnp.zeros(act_shape, act_dtype),  # cotangent
                              bufs0,                            # residuals
                              gacc0,
                              jnp.asarray(0.0, jnp.float32))    # loss acc

                    def tick(carry, t):
                        fwd_act, grad_in, bufs, gacc, loss_acc = carry
                        f_active, c_f, m_f = fwd_job(t)
                        b_active, c_b, m_b = bwd_job(t)
                        slot_b = c_b * K + jnp.mod(m_b, K)

                        # -- forward sub-step: ONE chunk (1/v stage) —
                        # cond-gated on the global window so drain
                        # ticks pay nothing
                        def do_fwd():
                            out, l_f, leaves = fwd_probe(
                                (pe, chunk_slice(pb, c_f), ph),
                                fwd_act, m_f, c_f,
                                jax.random.fold_in(k0, m_f))
                            return (out, l_f,
                                    [leaves[i] for i in var_idx])

                        def skip_fwd():
                            return (jnp.zeros(act_shape, act_dtype),
                                    jnp.asarray(0.0, jnp.float32),
                                    [jnp.zeros(
                                        tuple(leaf_shapes[i].shape),
                                        leaf_shapes[i].dtype)
                                     for i in var_idx])

                        out_f, loss_f, vleaves = lax.cond(
                            t < A * v + pp - 1, do_fwd, skip_fwd)
                        slot_f = jnp.where(
                            f_active, c_f * K + jnp.mod(m_f, K), nslots)
                        bufs = tuple(
                            lax.dynamic_update_index_in_dim(
                                buf, vl, slot_f, 0)
                            for buf, vl in zip(bufs, vleaves))
                        loss_acc = loss_acc + jnp.where(f_active, loss_f,
                                                        0.0)

                        # read AFTER the write: the only same-tick
                        # producer-consumer is the last virtual stage
                        # (same job), whose just-written slot holds
                        # exactly the wanted fresh residuals
                        gathered = [
                            lax.dynamic_index_in_dim(
                                buf, slot_b, 0, keepdims=False)
                            for buf in bufs]

                        # -- backward sub-step: pullback rebuilt from
                        # the slot + the chunk's weight-derived stack
                        def do_bwd():
                            leaves_b = [None] * len(leaf_var)
                            for stk, i in zip(inv_stacks, inv_idx):
                                leaves_b[i] = lax.dynamic_index_in_dim(
                                    stk, c_b, 0, keepdims=False)
                            for g, i in zip(gathered, var_idx):
                                leaves_b[i] = g
                            vjp_b = jax.tree_util.tree_unflatten(
                                box['treedef'], leaves_b)
                            g_out = jnp.where(
                                jnp.logical_and(stage == pp - 1,
                                                c_b == v - 1),
                                jnp.zeros(act_shape, act_dtype),
                                grad_in.astype(act_dtype))
                            return vjp_b((g_out, grad_cot()))

                        def skip_bwd():
                            return ((jax.tree_util.tree_map(
                                jnp.zeros_like, pe),
                                jax.tree_util.tree_map(
                                    lambda l: jnp.zeros(
                                        (per,) + l.shape[1:], l.dtype),
                                    pb),
                                jax.tree_util.tree_map(
                                    jnp.zeros_like, ph)),
                                jnp.zeros(act_shape, act_dtype))

                        (d_pe, d_pbc, d_ph), dx = lax.cond(
                            t >= D - (pp - 1), do_bwd, skip_bwd)
                        gacc = (accum_full(gacc[0], d_pe, b_active),
                                accum_chunk(gacc[1], d_pbc, c_b,
                                            b_active),
                                accum_full(gacc[2], d_ph, b_active))
                        dx = jnp.where(b_active, dx, jnp.zeros_like(dx))

                        if pp > 1:
                            nxt_act = lax.ppermute(
                                out_f, 'pp',
                                [(i, (i + 1) % pp) for i in range(pp)])
                            nxt_grad = lax.ppermute(
                                dx, 'pp',
                                [(i, (i - 1) % pp) for i in range(pp)])
                        else:
                            nxt_act, nxt_grad = out_f, dx
                        return (nxt_act, nxt_grad, bufs, gacc,
                                loss_acc), None
                else:
                    # -- recompute interleaved (chunk-input buffer) ------
                    carry0 = (jnp.zeros(act_shape, act_dtype),  # fwd act
                              jnp.zeros(act_shape, act_dtype),  # cotangent
                              jnp.zeros((nslots + 1,) + act_shape,
                                        act_dtype),             # inputs buf
                              gacc0,
                              jnp.asarray(0.0, jnp.float32))    # loss acc

                    def tick(carry, t):
                        fwd_act, grad_in, buf, gacc, loss_acc = carry
                        f_active, c_f, m_f = fwd_job(t)
                        b_active, c_b, m_b = bwd_job(t)
                        # read-before-write + same-JOB same-tick select
                        x_read = lax.dynamic_index_in_dim(
                            buf, c_b * K + jnp.mod(m_b, K), 0,
                            keepdims=False)
                        p_same = jnp.logical_and(
                            jnp.logical_and(m_f == m_b, c_f == c_b),
                            f_active)
                        x_saved = jnp.where(p_same, fwd_act, x_read)

                        def do_fwd():
                            return fwd_only(
                                pe, chunk_slice(pb, c_f), fwd_act,
                                m_f, c_f, jax.random.fold_in(k0, m_f))

                        out_f = lax.cond(
                            t < A * v + pp - 1, do_fwd,
                            lambda: jnp.zeros(act_shape, act_dtype))
                        slot_f = jnp.where(
                            f_active, c_f * K + jnp.mod(m_f, K), nslots)
                        buf = lax.dynamic_update_index_in_dim(
                            buf, fwd_act, slot_f, 0)

                        # -- backward: re-run the chunk from its saved
                        # input via a local vjp consumed this tick
                        def do_bwd():
                            k_b = jax.random.fold_in(k0, m_b)
                            (_out_p, loss_p), vjp_fn = jax.vjp(
                                lambda p3, x: full_fn(p3, x, m_b, c_b,
                                                      k_b),
                                (pe, chunk_slice(pb, c_b), ph), x_saved)
                            g_out = jnp.where(
                                jnp.logical_and(stage == pp - 1,
                                                c_b == v - 1),
                                jnp.zeros_like(_out_p),
                                grad_in.astype(_out_p.dtype))
                            d_p3, dx = vjp_fn((g_out, grad_cot()))
                            return d_p3, dx, loss_p

                        def skip_bwd():
                            return ((jax.tree_util.tree_map(
                                jnp.zeros_like, pe),
                                jax.tree_util.tree_map(
                                    lambda l: jnp.zeros(
                                        (per,) + l.shape[1:], l.dtype),
                                    pb),
                                jax.tree_util.tree_map(
                                    jnp.zeros_like, ph)),
                                jnp.zeros(act_shape, act_dtype),
                                jnp.asarray(0.0, jnp.float32))

                        (d_pe, d_pbc, d_ph), dx, loss_p = lax.cond(
                            t >= D - (pp - 1), do_bwd, skip_bwd)
                        gacc = (accum_full(gacc[0], d_pe, b_active),
                                accum_chunk(gacc[1], d_pbc, c_b,
                                            b_active),
                                accum_full(gacc[2], d_ph, b_active))
                        loss_acc = loss_acc + jnp.where(b_active, loss_p,
                                                        0.0)
                        dx = jnp.where(b_active, dx, jnp.zeros_like(dx))

                        if pp > 1:
                            nxt_act = lax.ppermute(
                                out_f, 'pp',
                                [(i, (i + 1) % pp) for i in range(pp)])
                            nxt_grad = lax.ppermute(
                                dx, 'pp',
                                [(i, (i - 1) % pp) for i in range(pp)])
                        else:
                            nxt_act, nxt_grad = out_f, dx
                        return (nxt_act, nxt_grad, buf, gacc,
                                loss_acc), None

                (_, _, _, gacc, loss_sum), _ = lax.scan(
                    tick, carry0, jnp.arange(T))
                grads = {'embed': gacc[0], 'blocks': gacc[1],
                         'head': gacc[2]}
                return self._reduce_and_update(
                    params, states, loss_sum / A, grads, lr, dp_on,
                    scale=scale if use_scaling else None)

        return self._finalize(step, dp_on)

    def _build_fthenb(self):
        A, pp = self.A, self.pp
        axes = self.axes
        embed, head = self.embed, self.head
        dp_on = 'dp' in axes and self.mesh.shape['dp'] > 1
        use_scaling = self._use_scaling
        stage_forward = self._make_stage_forward()

        def step(params, states, lr, scale, key, input_ids, labels):
            with C.spmd_region(axes):
                params = self._materialize_params(params)
                stage = lax.axis_index('pp') if pp > 1 else 0
                mb = input_ids.shape[0] // A

                def loss_of(ps):
                    pe, pb, ph = ps['embed'], ps['blocks'], ps['head']
                    k0 = key
                    if dp_on:
                        k0 = jax.random.fold_in(k0, lax.axis_index('dp'))

                    # Embed all microbatches — only stage 0 pays for it
                    # (stage==0 is uniform across each mp group, so the
                    # vocab-parallel psum inside the cond is deadlock-free).
                    def do_embed(_):
                        with bind_arrays(embed, pe):
                            with rng_mod.rng_guard(
                                    jax.random.fold_in(k0, 17)), \
                                    autograd.no_grad():
                                return embed(Tensor(input_ids)).data
                    H = None  # resolved below via eval_shape
                    emb_shape = jax.eval_shape(do_embed, 0)
                    if pp > 1:
                        emb_all = lax.cond(
                            stage == 0, do_embed,
                            lambda _: jnp.zeros(emb_shape.shape,
                                                emb_shape.dtype), 0)
                    else:
                        emb_all = do_embed(0)
                    emb_all = emb_all.reshape(A, mb, *emb_all.shape[1:])
                    labels_mb = labels.reshape(A, mb, *labels.shape[1:])

                    Lseq = emb_all.shape[2]
                    act0 = jnp.zeros((mb, Lseq, emb_all.shape[-1]),
                                     emb_all.dtype)
                    loss0 = jnp.asarray(0.0, jnp.float32)

                    def tick(carry, t):
                        act, loss_acc = carry
                        # stage 0 ingests microbatch t (clamped)
                        t_in = jnp.clip(t, 0, A - 1)
                        my_in = jnp.where(stage == 0,
                                          emb_all[t_in], act)
                        tick_key = jax.random.fold_in(k0, t)
                        out = stage_forward(pb, my_in, tick_key)
                        # last stage consumes microbatch t-(pp-1)
                        t_out = jnp.clip(t - (pp - 1), 0, A - 1)

                        def do_head(o):
                            with bind_arrays(head, ph):
                                with rng_mod.rng_guard(
                                        jax.random.fold_in(k0, 7919)), \
                                        autograd.no_grad():
                                    return head(
                                        Tensor(o),
                                        Tensor(labels_mb[t_out])).data \
                                        .astype(jnp.float32)
                        valid = ((stage == pp - 1) &
                                 (t >= pp - 1) & (t - (pp - 1) < A))
                        if pp > 1:
                            mb_loss = lax.cond(
                                valid, do_head,
                                lambda o: jnp.asarray(0.0, jnp.float32),
                                out)
                        else:
                            mb_loss = jnp.where(valid, do_head(out), 0.0)
                        loss_acc = loss_acc + mb_loss
                        # rotate activations to the next stage
                        if pp > 1:
                            nxt = lax.ppermute(
                                out, 'pp',
                                [(i, (i + 1) % pp) for i in range(pp)])
                        else:
                            nxt = out
                        return (nxt, loss_acc), None

                    (act, loss_sum), _ = lax.scan(
                        tick, (act0, loss0), jnp.arange(A + pp - 1))
                    # Return the LOCAL loss (nonzero only on the last
                    # stage). Reducing it here would run the psum transpose
                    # under every device's cotangent seed and scale grads by
                    # the stage count; value-level reductions happen after
                    # value_and_grad.
                    return loss_sum / A

                if use_scaling:
                    loss, grads = jax.value_and_grad(
                        lambda ps: loss_of(ps)
                        * scale.astype(jnp.float32))(params)
                    loss = loss / scale.astype(jnp.float32)
                else:
                    loss, grads = jax.value_and_grad(loss_of)(params)
                return self._reduce_and_update(
                    params, states, loss, grads, lr, dp_on,
                    scale=scale if use_scaling else None)

        return self._finalize(step, dp_on)

    def _update_one(self, p, g, st, lr):
        opt = self.optimizer
        low = p.dtype != jnp.float32
        master = st.pop('master', None)
        p32 = master if master is not None else (
            p.astype(jnp.float32) if low else p)
        g32 = g.astype(jnp.float32)
        wd = getattr(opt, '_weight_decay', None)
        if wd and opt._decay_into_grad():
            g32 = g32 + wd * p32
        np_, ns = opt.update(p32, g32, st, lr)
        ns = dict(ns)
        if master is not None:
            ns['master'] = np_
        return np_.astype(p.dtype), ns

    # ------------------------------------------------------------------------
    def _dispatch(self, data, scale=None, scaler=None):
        """Dispatch one pipelined step; returns an AsyncResult holding
        the device-resident loss + found-inf flag (+ taps). Deferred
        drain work: taps processing and — when a GradScaler rides along
        — its found-inf accounting, applied in submission order."""
        self._ensure_open()
        # gap bracket opens BEFORE any jax client call (batch asarray,
        # key fold-in, scale placement can serialize behind in-flight
        # compute — dispatch time, not inter-dispatch host gap)
        self._gap.dispatch_begin()
        if scaler is not None and scale is None \
                and scaler.is_enable():
            scale = scaler._scale
        input_ids, labels = data
        ii = input_ids.data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ll = labels.data if isinstance(labels, Tensor) \
            else jnp.asarray(labels)
        self._ledger.observe_batch(ii.shape)
        # microbatching contract, checked up front: the step reshapes
        # each dp rank's slice to [A, mb, ...] — a bad batch size used
        # to surface as an opaque reshape traceback from inside the
        # compiled step trace
        n = int(ii.shape[0]) if ii.ndim else 0
        if ll.ndim == 0 or int(ll.shape[0]) != n:
            raise PipelineBatchError(
                f"inputs and labels disagree on the batch dimension: "
                f"{tuple(ii.shape)} vs {tuple(ll.shape)}")
        dp = max(self.dp, 1)
        if n == 0 or n % (dp * self.A):
            raise PipelineBatchError(
                f"batch size {n} is not divisible by dp({dp}) x "
                f"accumulate_steps({self.A}); feed dp * A * "
                "micro_batch_size rows per step (adjust "
                "accumulate_steps or pipeline_configs)")
        want_scaling = scale is not None
        if not hasattr(self, '_compiled_by_mode'):
            self._compiled_by_mode = {}
        from ....core import memory as _mem
        if not hasattr(self, '_taps_on'):
            # latched at first build (taps change the compiled output
            # signature — set FLAGS before the first train_batch)
            from ....core import numerics as _num
            self._taps_on = _num.taps_enabled()
        if want_scaling != self._use_scaling or self._compiled is None:
            self._use_scaling = want_scaling
            # two-slot cache: alternating scaled/unscaled steps must not
            # recompile the pipeline each switch
            self._compiled = self._compiled_by_mode.get(want_scaling)
            if self._compiled is None:
                from .... import profiler as _prof
                with _prof.RecordEvent('pipeline::build',
                                       event_type='compile',
                                       pp=self.pp,
                                       scaling=want_scaling), \
                        _mem.phase('pipeline.build'):
                    self._compiled = self._build()
                self._compiled_by_mode[want_scaling] = self._compiled
        lr = self._lr.arg()
        sc = jnp.asarray(1.0 if scale is None else float(scale),
                         jnp.float32)
        key = rng_mod.next_key()
        from .... import profiler as _prof
        # each MODE's executable compiles on its first dispatch (minutes
        # at GPT scale; a later scaled/unscaled switch compiles again) —
        # _step_guard journals/heartbeats only warm dispatches
        if not hasattr(self, '_warm_modes'):
            self._warm_modes = set()
        first = want_scaling not in self._warm_modes
        args = (self._params, self._states, lr, sc, key, ii, ll)
        if not hasattr(self, '_exec_by_mode'):
            self._exec_by_mode = {}
        exe = self._exec_by_mode.get(want_scaling)
        if exe is None:
            # explicit AOT compile: lower/compile telemetry + the
            # buffer-assignment activation census
            # (ptpu_mem_activation_bytes; docs/performance.md
            # #remat-policy) for the pipeline step program
            exe, _ = _prof.compile_with_telemetry(
                self._compiled, 'pipeline.step', args)
            self._exec_by_mode[want_scaling] = exe
        with _prof.RecordEvent('pipeline::train_step', event_type='jit'), \
                self._step_guard(first, 'pipeline.train_step',
                                 'pipeline.step'):
            try:
                out = exe(*args)
            except TypeError:
                # AOT signature drift: fall back to the jitted fn
                if exe is self._compiled:
                    raise
                self._exec_by_mode[want_scaling] = self._compiled
                out = self._compiled(*args)
        self._gap.dispatch_end(depth=len(self._inflight) + 1)
        step_no = self._pp_step = getattr(self, '_pp_step', 0) + 1
        loss, self._params, self._states, found = out[:4]
        i = 4
        if self._lr.fn is not None:
            self._lr.carry = out[i]
            i += 1
        taps = out[i] if self._taps_on else None
        self._warm_modes.add(want_scaling)
        self.last_found_inf = found
        on_drain = None
        if taps is not None or scaler is not None:
            def on_drain(res, _t=taps, _s=step_no, _scaler=scaler):
                found_host = None
                if _t is not None:
                    found_host = self._process_taps(res.found_inf, _t,
                                                    step=_s)
                    self.last_found_inf = found_host
                if _scaler is not None:
                    if found_host is None:
                        from ....core import numerics as _num
                        found_host = bool(np.asarray(
                            _num._host_fetch(res.found_inf)))
                    # deferred found-inf accounting (ISSUE 13): same
                    # sequence the per-step path applies, at drain
                    _scaler.update_from_found(bool(found_host))
        return A_.AsyncResult(loss, step_no, found_inf=found, taps=taps,
                              on_drain=on_drain, monitor=self._gap)

    def train_batch(self, data, scale=None):
        """data = (input_ids, labels) covering dp_degree × A × micro_bs.
        `scale`: optional loss-scaling factor (fp16 GradScaler path); the
        step unscales grads, skips the update on non-finite gradients,
        and records `self.last_found_inf` for the scaler's dynamic
        update."""
        if len(self._inflight):
            # mixed APIs: drain queued async steps FIRST so deferred
            # work (taps/scaler accounting) keeps submission order
            self.flush()
        res = self._dispatch(data, scale=scale)
        res.wait()     # legacy per-step semantics (taps processed now)
        return Tensor(res.loss)

    def train_step(self, data, scaler=None):
        """Async dispatch (docs/performance.md#async-dispatch): returns
        an AsyncResult with the device-resident loss and found-inf flag
        — no host fetch. A GradScaler passed here has its found-inf read
        and dynamic-scale update deferred to the window-drain point, in
        submission order: the skip accounting is exact for the scales
        actually dispatched, but a scale CHANGE only reaches steps
        dispatched after its drain (up to `window` steps later than the
        per-step path — scale-induced overflows can therefore resolve
        one window later; docs/performance.md#async-dispatch).
        `flush()` drains everything."""
        return self._inflight.push(self._dispatch(data, scaler=scaler))

    def input_sharding(self, index, ndim):
        """DeviceLoader contract: batch tensors are dp-sharded on axis 0
        (replicated when dp=1)."""
        dp_on = 'dp' in self.axes and self.mesh.shape['dp'] > 1
        return NamedSharding(self.mesh, P('dp') if dp_on else P())

    def _process_taps(self, found, taps, step=None):
        """Fetch found_inf + the taps pytree in ONE host sync; returns
        the host-side found flag for last_found_inf."""
        from ....core import numerics as _num
        found_host, taps_host = _num._host_fetch((found, taps))
        if bool(found_host):
            # loss-scale overflow the compiled step already survived
            # (update skipped via found_inf): the post-unscale grads are
            # nonfinite BY DESIGN — raising NumericsError here, or
            # folding inf into the grad-norm gauges/histogram, would
            # punish the GradScaler's routine scale probe (the eager AMP
            # skip path drops the guard state for the same reason)
            self.last_numerics = None
            return found_host
        taps = taps_host    # already on host: the fetch inside
                            # process_jit_taps is a free no-op
        meta = {kind: dict(self._tap_shapes)
                for kind in ('grads', 'params')}
        self.last_numerics = _num.process_jit_taps(
            taps, site='pipeline',
            step=getattr(self, '_pp_step', None) if step is None
            else step,
            meta=meta)
        return found_host

    def sync_model(self):
        self._ensure_open()
        self.flush()    # every dispatched step lands before the copy-out
        for n, p in self._embed_named:
            if n in self._params['embed']:
                p._data = self._params['embed'][n]
        for n, p in self._head_named:
            if n in self._params['head']:
                p._data = self._params['head'][n]
        # stacked row i holds blocks[self._layer_order[i]] (chunk-major
        # under the interleaved schedule; identity otherwise)
        for row, j in enumerate(self._layer_order):
            lookup = dict(self.blocks[j].named_parameters())
            for n, _ in self._block_named:
                if n in self._params['blocks']:
                    lookup[n]._data = self._params['blocks'][n][row]
        if getattr(self, '_pp_overlap', False):
            # reconstruct bucketed params from the [pp, size] flat
            # shards: blocks rows are stage-local slices in stage
            # order; embed/head rows replicate (row 0 is the value).
            # These are the EXACT updated values — under an int8 wire
            # the compiled forward sees the block-rounded gathered
            # copy, but the shards are the trajectory
            # (docs/performance.md#comm-overlap).
            pp = max(self.pp, 1)
            blk_lookup = [dict(b.named_parameters())
                          for b in self.blocks]
            for b, sh in zip(self._pp_layout.buckets,
                             self._params['_shards']):
                host = np.asarray(jax.device_get(sh))  # [pp, size]
                for s in b.slots:
                    grp, n = s.name.split('/', 1)
                    if grp == 'blocks':
                        per = s.shape[0]
                        for k in range(pp):
                            rows = host[k, s.offset:s.offset + s.size] \
                                .reshape(s.shape)
                            for j in range(per):
                                blk_lookup[self._layer_order[
                                    k * per + j]][n]._data = \
                                    jnp.asarray(rows[j])
                    else:
                        named = dict(self._embed_named if grp == 'embed'
                                     else self._head_named)
                        named[n]._data = jnp.asarray(
                            host[0, s.offset:s.offset + s.size]
                            .reshape(s.shape))

    # shutdown()/close() from EngineTeardown
