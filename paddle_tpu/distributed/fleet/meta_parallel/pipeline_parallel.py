"""Pipeline-parallel engine.

Reference parity: fleet/meta_parallel/pipeline_parallel.py:33
(PipelineParallel.train_batch:114 — slice batch into accumulate_steps
microbatches, F-then-B schedule, _send_meta/_recv_meta first-iteration
handshake, allreduce_shared_weight_gradients, _reduce_final_loss) and the
static 1F1B SectionWorker (section_worker.cc:134-185).

TPU-native execution model: a single-controller SPMD program. Stage weights
live stacked over the 'pp' mesh axis; one jitted step runs the full 1F1B-
equivalent schedule as a `lax.scan` over microbatches with
`collective-permute` moving activations between neighbor stages over ICI
(the spmd_pipeline module). This wrapper keeps the reference's train_batch
API: in hybrid runs it drives the SPMD engine; with pp_degree==1 it reduces
to microbatch gradient accumulation.
"""
import numpy as np

from ....core.tensor import Tensor
from ....ops import manip
from .meta_parallel_base import MetaParallelBase


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__(layers, hcg, strategy)
        conf = (strategy.pipeline_configs if strategy is not None
                else {'accumulate_steps': 1, 'micro_batch_size': 1})
        self.accumulate_steps = conf.get('accumulate_steps', 1)
        self.micro_batch_size = conf.get('micro_batch_size', 1)
        self.schedule_mode = conf.get('schedule_mode', '1F1B')
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None
        self._spmd_engine = None

    def is_pipeline_first_stage(self):
        return self.stage_id == 0

    def is_pipeline_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def _load_micro_batch(self, data, micro_step):
        """Parity: pipeline_parallel.py:_load_micro_batch:241."""
        inputs, labels = data
        begin = micro_step * self.micro_batch_size
        end = begin + self.micro_batch_size

        def slice_one(x):
            if x is None:
                return None
            if isinstance(x, (list, tuple)):
                return type(x)(slice_one(v) for v in x)
            t = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
            return t[begin:end]
        return slice_one(inputs), slice_one(labels)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Parity: pipeline_parallel.py train_batch:114."""
        if self.num_stages > 1:
            return self._train_batch_spmd(data, optimizer, lr_scheduler,
                                          scaler)
        # pp_degree==1: pure microbatch accumulation (F-then-B trivially).
        self._layers.train()
        total_loss = None
        for mb in range(self.accumulate_steps):
            inp, lab = self._load_micro_batch(data, mb)
            out = self._layers(*(inp if isinstance(inp, tuple) else (inp,)))
            loss = self._layers._loss_fn(out, *(lab if isinstance(
                lab, tuple) else (lab,))) if hasattr(
                    self._layers, '_loss_fn') and \
                self._layers._loss_fn is not None else out
            from ....ops import math as M
            scaled = M.scale(loss, 1.0 / self.accumulate_steps)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total_loss = scaled if total_loss is None \
                else total_loss + scaled
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss

    def _train_batch_spmd(self, data, optimizer, lr_scheduler=None,
                          scaler=None):
        from .spmd_pipeline import engine_from_pipeline_layer
        if self._spmd_engine is None:
            inner = getattr(optimizer, '_inner_opt', optimizer)
            self._spmd_engine = engine_from_pipeline_layer(
                self._layers, inner, self.accumulate_steps,
                schedule=self.schedule_mode)
        inputs = data[0]
        n = (inputs.shape[0] if hasattr(inputs, 'shape')
             else len(inputs))
        dp = self._hcg.get_data_parallel_world_size()
        expect = dp * self.accumulate_steps * self.micro_batch_size
        if n != expect:
            raise ValueError(
                f"batch size {n} != dp({dp}) x accumulate_steps"
                f"({self.accumulate_steps}) x micro_batch_size"
                f"({self.micro_batch_size}); adjust pipeline_configs")
        if scaler is not None and scaler.is_enable():
            # fp16 loss scaling through the pipeline (parity:
            # hybrid_parallel_gradscaler.py): the engine scales the
            # differentiated loss, unscales grads, skips the update on a
            # global found_inf, and the scaler's dynamic schedule runs on
            # the returned flag
            loss = self._spmd_engine.train_batch(data,
                                                 scale=scaler._scale)
            scaler._found_inf = bool(
                np.asarray(self._spmd_engine.last_found_inf))
            scaler._update()
        else:
            loss = self._spmd_engine.train_batch(data)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def sync_model(self):
        """Pull the engine's trained weights back into the full-model
        layers the engine was built from (state_dict()/eval_batch read
        through these)."""
        if self._spmd_engine is not None:
            self._spmd_engine.sync_model()

    def state_dict(self, *args, **kwargs):
        if self._spmd_engine is not None:
            self._spmd_engine.sync_model()
            sd = {}
            for n, p in self._spmd_engine.embed.named_parameters():
                sd[f"embed.{n}"] = p
            for i, b in enumerate(self._spmd_engine.blocks):
                for n, p in b.named_parameters():
                    sd[f"blocks.{i}.{n}"] = p
            for n, p in self._spmd_engine.head.named_parameters():
                sd[f"head.{n}"] = p
            return sd
        return self._layers.state_dict(*args, **kwargs)

    def eval_batch(self, data, compute_loss=False):
        self._layers.eval()
        inp, lab = self._load_micro_batch(data, 0)
        out = self._layers(*(inp if isinstance(inp, tuple) else (inp,)))
        if compute_loss and getattr(self._layers, '_loss_fn', None):
            return self._layers._loss_fn(out, *(lab if isinstance(
                lab, tuple) else (lab,)))
        return out

    def allreduce_shared_weight_gradients(self):
        """Parity: A.4 — tied-weight grad sync across holding stages. In the
        SPMD engine the psum over 'pp' of the stacked shared grads does this
        inside the compiled step."""
        pass

    def _reduce_final_loss(self, loss):
        return loss
