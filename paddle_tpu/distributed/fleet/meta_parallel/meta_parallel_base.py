"""Base wrapper for meta-parallel engines (parity:
fleet/meta_parallel/meta_parallel_base.py)."""
import contextlib

from ....nn.layer.base import Layer


class EngineTeardown:
    """Shared device-state teardown for the SPMD engines (the r5 bench
    regression: without it a finished engine pins params + optimizer
    states + executables in HBM for the process lifetime).

    `shutdown()` (alias `close()`) disarms the watchdog heartbeat, drops
    the compiled executables and every engine-owned device buffer,
    records an `engine.shutdown` accounting phase whose census proves
    the release, and returns a post-release memory sample. Idempotent; a
    shut-down engine refuses further work via `_ensure_open()`.
    """

    _closed = False

    def _ensure_open(self):
        if getattr(self, '_closed', False):
            raise RuntimeError(
                f"{type(self).__name__} was shut down; device state is "
                "gone — build a new engine to keep training (sync_model "
                "before shutdown to keep a host copy)")

    @contextlib.contextmanager
    def _step_guard(self, first, site, phase):
        """Diagnostics bracket for one engine dispatch: flight-recorder
        journal + step heartbeat + env-gated watchdog on WARM steps
        only (`first` marks a dispatch that will XLA-compile — minutes
        at scale — which must not age against the hang deadline), plus
        the OOM guard and memory phase on every dispatch. Shared by
        both engines so the cold-start exemption policy can't drift."""
        from ....core import memory as _mem
        from ... import flight_recorder as _fr
        if not first:
            _fr.start_watchdog()   # no-op unless PADDLE_HANG_TIMEOUT set
            _fr.heartbeat()
        span = contextlib.nullcontext() if first else \
            _fr.record_span(site, mode='exec')
        with span, _mem.oom_guard(site), _mem.phase(phase):
            yield

    def shutdown(self):
        from ....core import memory as _mem
        from ... import flight_recorder as _fr
        if getattr(self, '_closed', False):
            return _mem.sample(count_buffers=True)
        _fr.engine_teardown()    # a stale heartbeat after a deliberate
                                 # stop must not fire the hang watchdog
        inflight = getattr(self, '_inflight', None)
        if inflight is not None:
            # drop (not drain) the async dispatch window: the results'
            # device buffers must not outlive the engine
            inflight.clear()
        gap = getattr(self, '_gap', None)
        if gap is not None:
            # stop telemetry from reporting a dead engine's host-gap
            # stats (host_snapshot walks the registry)
            from ....core import async_step as _async_step
            _async_step.unregister_monitor(gap)
        with _mem.phase('engine.shutdown'):
            self._compiled = None
            if hasattr(self, '_compiled_by_mode'):
                self._compiled_by_mode = {}
            if hasattr(self, '_exec'):
                self._exec = None        # AOT executables pin buffers too
            if hasattr(self, '_exec_by_mode'):
                self._exec_by_mode = {}
            self._params = None
            self._states = None
            if hasattr(self, '_param_shards'):
                # deferred-gather engines (comm_overlap) keep bucketed
                # params as flat shards beside _params
                self._param_shards = None
            self._closed = True
            import gc
            gc.collect()     # the donated-buffer graph can hold cycles
        return _mem.sample(count_buffers=True)

    close = shutdown


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self
