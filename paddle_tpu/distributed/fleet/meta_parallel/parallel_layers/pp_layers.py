"""Pipeline layer partitioning.

Reference parity: fleet/meta_parallel/parallel_layers/pp_layers.py —
SegmentLayers:23 (uniform/param-weighted split), LayerDesc:44,
SharedLayerDesc:62, PipelineLayer:77 (builds only this stage's segment;
shared-weight comm groups A.4). The partitioning math is identical; the
execution engine (meta_parallel/pipeline_parallel.py) drives stages with XLA
collectives instead of SectionWorker threads.
"""
import math

import numpy as np

from .....nn.layer.base import Layer
from .....nn.layer.container import LayerList, Sequential


class LayerDesc:
    """Parity: pp_layers.py:44 — lazy layer constructor."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Parity: pp_layers.py:62 — layers shared across stages (e.g. tied
    embeddings)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr='weight', *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Parity: pp_layers.py:23."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        assert self.num_items >= self.num_parts

    def do_segment(self):
        if self.method == "uniform":
            return self.uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            # cut by named layer class occurrences
            name = self.method.split(':', 1)[1]
            hits = [0]
            for i, d in enumerate(self._layers_desc):
                cls = d.layer_func if isinstance(d, LayerDesc) else type(d)
                if getattr(cls, '__name__', '') == name:
                    hits.append(i)
            hits.append(self.num_items)
            # merge into num_parts contiguous groups
            per = max(1, (len(hits) - 1) // self.num_parts)
            result = [0]
            for p in range(1, self.num_parts):
                result.append(hits[min(p * per, len(hits) - 2)])
            result.append(self.num_items)
            return result
        raise ValueError(f"bad segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extras = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extras else 0)
        return result


class PipelineLayer(Layer):
    """Parity: pp_layers.py:77. Holds ALL segment descriptions; materializes
    only this stage's layers. run_function() exposes the local chunk to the
    engine."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        # honored by the SPMD engine (engine_from_pipeline_layer ->
        # schedule='interleaved'); was accepted-and-dropped before
        if num_virtual_pipeline_stages is not None:
            num_virtual_pipeline_stages = int(num_virtual_pipeline_stages)
            if num_virtual_pipeline_stages < 1:
                raise ValueError(
                    "num_virtual_pipeline_stages must be >= 1, got "
                    f"{num_virtual_pipeline_stages}")
        self._num_virtual_pipeline_stages = num_virtual_pipeline_stages
        if num_stages is None and topology is None:
            num_stages = 1
        from ... import fleet as fleet_singleton
        hcg = fleet_singleton._hcg
        if hcg is not None:
            self._num_stages = hcg.get_pipe_parallel_world_size()
            self._stage_id = hcg.get_stage_id()
        else:
            self._num_stages = num_stages or 1
            self._stage_id = 0

        self.segment_parts = SegmentLayers(
            self._layers_desc, self._num_stages, seg_method).do_segment()
        self._start = self.segment_parts[self._stage_id]
        self._end = self.segment_parts[self._stage_id + 1]

        self.run_function = []
        self._shared_layers = {}
        self.shared_weight_keys = []
        for i in range(self._start, self._end):
            self._build_one(i)

        # register built layers so parameters() sees them
        for idx, f in enumerate(self.run_function):
            if isinstance(f, Layer):
                self.add_sublayer(str(idx), f)

    def _build_one(self, i):
        desc = self._layers_desc[i]
        if isinstance(desc, SharedLayerDesc):
            if desc.layer_name not in self._shared_layers:
                self._shared_layers[desc.layer_name] = desc.build_layer()
                self.shared_weight_keys.append(desc.layer_name)
            layer = self._shared_layers[desc.layer_name]
            if desc.forward_func is None:
                self.run_function.append(layer)
            else:
                import functools
                self.run_function.append(
                    functools.partial(desc.forward_func, layer))
        elif isinstance(desc, LayerDesc):
            self.run_function.append(desc.build_layer())
        else:
            self.run_function.append(desc)

    def get_stage_from_index(self, layer_idx):
        for stage in range(self._num_stages):
            if self.segment_parts[stage] <= layer_idx \
                    < self.segment_parts[stage + 1]:
                return stage
        raise ValueError("index out of range")

    def get_num_stages(self):
        return self._num_stages

    @property
    def parameters_desc(self):
        return self._layers_desc

    def forward(self, input, chunk_id=None):
        # eager-parity path: every recompute_interval-th layer re-forwards
        # in its backward (fleet.utils.recompute). The compiled twin
        # (engine_from_pipeline_layer) honors a nonzero interval by
        # forcing trace-level remat on, with the resolved policy deciding
        # the save/recompute split (docs/performance.md#remat-policy).
        x = input
        for i, f in enumerate(self.run_function):
            if self._recompute_interval > 0 and \
                    i % self._recompute_interval == 0 and \
                    not isinstance(x, tuple):
                from ...utils.recompute import recompute
                x = recompute(f, x)
            else:
                x = f(*x) if isinstance(x, tuple) else f(x)
        return x

    def build_full_model(self):
        """Materialize ALL stages' layers (used by the SPMD pipeline engine
        that holds every stage's weights stacked over the 'pp' mesh axis)."""
        funcs = []
        shared = {}
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in shared:
                    shared[desc.layer_name] = desc.build_layer()
                layer = shared[desc.layer_name]
                if desc.forward_func is None:
                    funcs.append(layer)
                else:
                    import functools
                    funcs.append(functools.partial(desc.forward_func, layer))
            elif isinstance(desc, LayerDesc):
                funcs.append(desc.build_layer())
            else:
                funcs.append(desc)
        return funcs, shared
