from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,
                        RowParallelLinear, ParallelCrossEntropy)
from .pp_layers import (LayerDesc, SharedLayerDesc, SegmentLayers,
                        PipelineLayer)
from .random_ import (RNGStatesTracker, get_rng_state_tracker,
                      model_parallel_random_seed)
