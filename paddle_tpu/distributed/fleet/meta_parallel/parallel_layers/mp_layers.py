"""Megatron-style tensor-parallel layers.

Reference parity: fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding:30, ColumnParallelLinear:97, RowParallelLinear:170,
ParallelCrossEntropy:249.

TPU-native (single-controller) design: each logical parameter is ONE
global-shaped array annotated with `split_axis` metadata. The hybrid engine
runs the layer inside `shard_map` with in_spec P(...,'mp') on that axis, so
the forward below sees the LOCAL shard — exactly the per-rank view the
reference's multi-process layers hold — and the explicit collectives
(_c_identity/_mp_allreduce/_c_concat/psum) lower to XLA collectives on the
'mp' mesh axis. Outside an SPMD region the same code degrades to the dense
layer (collectives are identities, the "shard" is the whole array), which is
also what the reference does at mp_degree=1.
"""
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn.layer.base import Layer
from .....nn import initializer as I
from .....ops import nn_ops as F
from .... import collective as C


def _mp_info(mp_group=None):
    """(world_size, rank, group) for the model-parallel axis."""
    from ... import fleet as fleet_singleton
    hcg = fleet_singleton._hcg
    if mp_group is not None:
        return mp_group.nranks, max(mp_group.rank, 0), mp_group
    if hcg is not None:
        return (hcg.get_model_parallel_world_size(),
                hcg.get_model_parallel_rank(),
                hcg.get_model_parallel_group())
    return 1, 0, None


def _mark(p, split_axis):
    p.is_distributed = True
    p.split_axis = split_axis
    return p


class VocabParallelEmbedding(Layer):
    """Parity: mp_layers.py:30 — vocab dim sharded across mp ranks
    (split_axis=0 on the global [V, D] table)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        assert num_embeddings % self.world_size == 0
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if self.world_size > 1:
            _mark(self.weight, 0)

    def forward(self, x):
        if not (self.world_size > 1 and C.in_spmd_region()):
            return F.embedding(x, self.weight)
        return C._c_embedding(self.weight, x, start_index=None,
                              group=self.group)


class ColumnParallelLinear(Layer):
    """Parity: mp_layers.py:97 — global weight [in, out], sharded on the
    out dim (split_axis=1). Forward: c_identity → local matmul → optional
    c_concat."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        assert out_features % self.world_size == 0
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        has_bias = True if has_bias is None else has_bias
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        if self.world_size > 1:
            _mark(self.weight, 1)
            if self.bias is not None:
                _mark(self.bias, 0)

    def forward(self, x, with_bias=True):
        """`with_bias=False` returns the pre-bias matmul so callers can
        fuse the bias-add into the next op (GPTMLP routes it into the
        bias+GELU Pallas kernel). Only valid with gather_output=False:
        the output then stays column-local like the bias shard, so the
        deferred add is mp-degree-transparent; a gathered output is
        full-width while self.bias is the local shard, and the deferred
        add would be shape-wrong — refuse it."""
        spmd = self.world_size > 1 and C.in_spmd_region()
        if not with_bias and spmd and self.gather_output:
            raise ValueError(
                "ColumnParallelLinear(with_bias=False) with "
                "gather_output=True under mp>1: the gathered output is "
                "full-width but self.bias is the local column shard — "
                "apply the bias in-layer (with_bias=True) instead")
        if spmd:
            if C.mp_seq_sharded():
                # sequence-parallel segment ends here: rebuild the full
                # token dim from the scattered slices (the AG half of
                # the Megatron RS/AG pair — docs/performance.md
                # #sequence-parallel-activations)
                x = C._c_allgather_seq(x, group=self.group)
            else:
                x = C._c_identity(x, group=self.group)
        out = F.linear(x, self.weight, self.bias if with_bias else None)
        if spmd and self.gather_output:
            out = C._c_concat(out, group=self.group)
        return out


class RowParallelLinear(Layer):
    """Parity: mp_layers.py:170 — global weight [in, out], sharded on the
    in dim (split_axis=0). Forward: (split input) → local matmul →
    mp_allreduce → +bias (bias replicated)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        assert in_features % self.world_size == 0
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        if self.world_size > 1:
            _mark(self.weight, 0)

    def forward(self, x):
        spmd = self.world_size > 1 and C.in_spmd_region()
        if not spmd:
            return F.linear(x, self.weight, self.bias)
        if not self.input_is_parallel:
            x = C._c_split(x, group=self.group)
        out = F.linear(x, self.weight)
        if C.mp_seq_sharded():
            # sequence-parallel segment starts here: the partial sums
            # psum_scatter along the token dim (same wire bytes as the
            # allreduce, 1/mp resident bytes in the elementwise segment
            # that follows); the bias is per-feature, so adding it to
            # the token slice is exact
            out = C._c_reduce_scatter_seq(out, group=self.group)
        else:
            out = C._mp_allreduce(out, group=self.group)
        if self.bias is not None:
            from .....ops import math as M
            out = M.add(out, self.bias)
        return out


class ParallelCrossEntropy(Layer):
    """Parity: mp_layers.py:249 — vocab-parallel softmax cross entropy over
    class-dim-sharded logits."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if not (self.world_size > 1 and C.in_spmd_region()):
            return F.softmax_with_cross_entropy(
                input, label, ignore_index=self.ignore_index)
        return C._c_softmax_with_cross_entropy(
            input, label, group=self.group, ignore_index=self.ignore_index)
