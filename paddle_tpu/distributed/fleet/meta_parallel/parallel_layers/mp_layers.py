"""Megatron-style tensor-parallel layers.

Reference parity: fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding:30, ColumnParallelLinear:97, RowParallelLinear:170,
ParallelCrossEntropy:249. TPU-native: weights carry their shard (this rank's
slice); matmuls stay full-size MXU calls; the comm primitives
(_c_identity/_mp_allreduce/_c_concat/c_embedding psum) lower to XLA
collectives on the 'mp' mesh axis inside the SPMD train step. Outside an
SPMD region (single device) the layers degrade to their dense equivalents
with mp_degree=1.
"""
import numpy as np

from .....core.tensor import Tensor
from .....nn.layer.base import Layer
from .....nn import initializer as I
from .....ops import nn_ops as F
from .... import collective as C


def _mp_info(mp_group=None):
    """(world_size, rank, group) for the model-parallel axis."""
    try:
        from ... import fleet as fleet_mod
    except ImportError:
        fleet_mod = None
    from ... import fleet
    hcg = fleet.fleet._hcg if fleet.fleet._hcg is not None else None
    if mp_group is not None:
        return mp_group.nranks, max(mp_group.rank, 0), mp_group
    if hcg is not None:
        return (hcg.get_model_parallel_world_size(),
                hcg.get_model_parallel_rank(),
                hcg.get_model_parallel_group())
    return 1, 0, None


class VocabParallelEmbedding(Layer):
    """Parity: mp_layers.py:30 — vocab dim sharded across mp ranks."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        assert num_embeddings % self.world_size == 0
        self.num_embeddings = num_embeddings
        self.per_part_size = num_embeddings // self.world_size
        self.vocab_start_index = self.rank * self.per_part_size
        self.weight = self.create_parameter(
            [self.per_part_size, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight.is_distributed = self.world_size > 1

    def forward(self, x):
        if self.world_size == 1:
            return F.embedding(x, self.weight)
        return C._c_embedding(self.weight, x,
                              start_index=self.vocab_start_index,
                              group=self.group)


class ColumnParallelLinear(Layer):
    """Parity: mp_layers.py:97 — weight [in, out/mp]; forward =
    c_identity → matmul (→ optional all-gather of outputs)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        assert out_features % self.world_size == 0
        self.out_per_part = out_features // self.world_size
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, self.out_per_part], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight.is_distributed = self.world_size > 1
        if has_bias is None:
            has_bias = True
        self.bias = self.create_parameter(
            [self.out_per_part], is_bias=True) if has_bias else None
        if self.bias is not None:
            self.bias.is_distributed = self.world_size > 1

    def forward(self, x):
        if self.world_size > 1:
            x = C._c_identity(x, group=self.group)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output and self.world_size > 1:
            out = C._c_concat(out, group=self.group)
        return out


class RowParallelLinear(Layer):
    """Parity: mp_layers.py:170 — weight [in/mp, out]; forward = (split
    input) → matmul → mp_allreduce(+bias)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        assert in_features % self.world_size == 0
        self.in_per_part = in_features // self.world_size
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [self.in_per_part, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.weight.is_distributed = self.world_size > 1
        self.bias = self.create_parameter(
            [out_features], is_bias=True) if has_bias else None
        # bias added AFTER allreduce → replicated, not distributed

    def forward(self, x):
        if self.world_size == 1:
            return F.linear(x, self.weight, self.bias)
        if not self.input_is_parallel:
            x = C._c_split(x, group=self.group)
        out = F.linear(x, self.weight)
        out = C._mp_allreduce(out, group=self.group)
        if self.bias is not None:
            from .....ops import math as M
            out = M.add(out, self.bias)
        return out


class ParallelCrossEntropy(Layer):
    """Parity: mp_layers.py:249 — vocab-parallel softmax cross entropy."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.world_size, self.rank, self.group = _mp_info(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if self.world_size == 1:
            return F.softmax_with_cross_entropy(input, label)
        return C._c_softmax_with_cross_entropy(
            input, label, group=self.group, ignore_index=self.ignore_index)
