"""TP RNG state tracker.

Reference parity: fleet/meta_parallel/parallel_layers/random.py:24
RNGStatesTracker — named RNG states so dropout differs across mp ranks while
weight init stays replicated. TPU-native: jax.random key folding per
(name, mp_rank) (SURVEY.md A.5 mapping note).
"""
import contextlib

import jax

from .....core import rng as rng_mod

MODEL_PARALLEL_RNG = 'model_parallel_rng'


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f'seed {seed} already exists')
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f'state {name} already exists')
        self.states_[name] = (jax.random.key(seed), 0)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f'state {name} does not exist')
        key, counter = self.states_[name]
        saved = rng_mod.get_rng_state()
        rng_mod.set_rng_state((key, counter))
        try:
            yield
        finally:
            self.states_[name] = rng_mod.get_rng_state()
            rng_mod.set_rng_state(saved)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    """Parity: random.py model_parallel_random_seed."""
    from ... import fleet as fleet_singleton
    hcg = fleet_singleton._hcg
    rank = hcg.get_model_parallel_rank() if hcg else 0
    if seed:
        global_seed = seed
        local_seed = seed * 1024 + rank * 100
    else:
        global_seed = 100
        local_seed = 41000 + rank
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add(MODEL_PARALLEL_RNG, local_seed)
    rng_mod.seed(global_seed)


@contextlib.contextmanager
def dropout_with_rng_tracker(name=MODEL_PARALLEL_RNG):
    tracker = get_rng_state_tracker()
    if name in tracker.states_:
        with tracker.rng_state(name):
            yield
    else:
        yield
