"""Fleet distributed metrics.

Reference parity: python/paddle/distributed/fleet/metrics/metric.py —
global sum/max/min/auc/acc aggregated across trainers (the reference uses
Gloo/collective allreduce; here the TCPStore host-collective backend when
multi-process, identity single-process)."""
import numpy as np

from ....core.tensor import Tensor


def _all_reduce(arr, op='sum'):
    import os
    nproc = int(os.environ.get('PADDLE_TRAINERS_NUM', '1') or '1')
    if nproc <= 1:
        return np.asarray(arr, np.float64)
    from ...host_collectives import host_group, init_host_collectives
    g = host_group() or init_host_collectives()
    return g.all_reduce(np.asarray(arr, np.float64), op)


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.data)
    return np.asarray(x)


def sum(input, scope=None, util=None):            # noqa: A001
    """Parity: fleet.metrics.sum — global sum across trainers."""
    return float(_all_reduce(_np(input).sum(), 'sum'))


def max(input, scope=None, util=None):            # noqa: A001
    return float(_all_reduce(_np(input).max(), 'max'))


def min(input, scope=None, util=None):            # noqa: A001
    return float(_all_reduce(_np(input).min(), 'min'))


def acc(correct, total, scope=None, util=None):
    """Parity: fleet.metrics.acc — global accuracy."""
    c = _all_reduce(_np(correct).sum(), 'sum')
    t = _all_reduce(_np(total).sum(), 'sum')
    return float(c) / float(np.maximum(t, 1e-12))


def mae(abserr, total_ins_num, scope=None, util=None):
    e = _all_reduce(_np(abserr).sum(), 'sum')
    n = _all_reduce(np.asarray(float(np.asarray(total_ins_num).sum()
                    if not np.isscalar(total_ins_num)
                    else total_ins_num)), 'sum')
    return float(e) / float(np.maximum(n, 1e-12))


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    e = _all_reduce(_np(sqrerr).sum(), 'sum')
    n = _all_reduce(np.asarray(float(np.asarray(total_ins_num).sum()
                    if not np.isscalar(total_ins_num)
                    else total_ins_num)), 'sum')
    return float(np.sqrt(e / np.maximum(n, 1e-12)))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Parity: fleet.metrics.auc — global AUC from per-trainer
    positive/negative prediction-bucket histograms (the reference's
    distributed AUC recipe: allreduce the buckets, then trapezoid)."""
    pos = _all_reduce(_np(stat_pos).reshape(-1), 'sum')
    neg = _all_reduce(_np(stat_neg).reshape(-1), 'sum')
    # walk buckets from high score to low, accumulating tp/fp
    tot_pos = new_pos = 0.0
    tot_neg = new_neg = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = tot_pos + pos[i]
        new_neg = tot_neg + neg[i]
        area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
        tot_pos, tot_neg = new_pos, new_neg
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    return float(area / (tot_pos * tot_neg))
