"""paddle_tpu.distributed.fleet — module-as-singleton API.

Reference parity: fleet/__init__.py:16-80 — exports Fleet /
DistributedStrategy / role makers / topology classes, and re-binds a
singleton `fleet = Fleet()` whose methods are module-level functions.
"""
from .base.fleet_base import Fleet, UtilBase
from .base.distributed_strategy import DistributedStrategy
from .base.role_maker import (PaddleCloudRoleMaker, UserDefinedRoleMaker,
                              Role)
from .base.topology import (CommunicateTopology, HybridCommunicateGroup,
                            ParallelMode)
from .dataset import (DatasetBase, InMemoryDataset, QueueDataset,
                      FileInstantDataset, BoxPSDataset)
from .data_generator import (MultiSlotDataGenerator,
                             MultiSlotStringDataGenerator)
from . import data_generator
from . import meta_parallel
from . import metrics
from . import meta_optimizers
from . import utils
from .meta_optimizers.dygraph_optimizer import (HybridParallelOptimizer,
                                                DygraphShardingOptimizer,
                                                HybridParallelGradScaler)
from .utils.recompute import recompute

fleet = Fleet()

# module-level singleton methods (parity: fleet/__init__.py re-binding)
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
is_worker = fleet.is_worker
is_server = fleet.is_server
worker_endpoints = fleet.worker_endpoints
server_endpoints = fleet.server_endpoints
server_num = fleet.server_num
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
minimize = fleet.minimize
save_persistables = fleet.save_persistables
save = fleet.save
shrink = fleet.shrink


def worker_index():
    return fleet._role_maker.worker_index() if fleet._role_maker else 0


def util():
    return fleet.util
