"""Fleet — the distributed orchestrator.

Reference parity: fleet/base/fleet_base.py:72 — init:139 (role maker +
hybrid topology _init_hybrid_parallel_env:291), distributed_optimizer:783,
distributed_model:836 (dispatch on parallel mode, :895-911), minimize:1288
(static meta-optimizer path), plus worker/server queries and save APIs.
"""
import os

import numpy as np

from ...env import parallel_env, get_rank, get_world_size
from ... import collective as C
from .distributed_strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode


class Fleet:
    """Parity: fleet_base.py:72 (module-level singleton `fleet`)."""

    def __init__(self):
        self._role_maker = None
        self._is_collective = False
        self._user_defined_strategy = None
        self._hcg = None
        self._topology = None
        self.strategy_compiler = None

    # -- init -----------------------------------------------------------------
    def init(self, role_maker=None, is_collective=False, strategy=None):
        """Parity: fleet_base.py init:139."""
        self._is_collective = is_collective or role_maker is None
        if role_maker is None:
            self._role_maker = PaddleCloudRoleMaker(
                is_collective=self._is_collective)
        else:
            self._role_maker = role_maker
        self._user_defined_strategy = strategy or DistributedStrategy()
        C.init_parallel_env()
        hybrid = self._user_defined_strategy.hybrid_configs
        if any(hybrid.get(k, 1) > 1 for k in
               ('mp_degree', 'pp_degree', 'sharding_degree', 'sep_degree')) \
                or hybrid.get('dp_degree', -1) > 1 or self._is_collective:
            self._init_hybrid_parallel_env()
        return self

    def _init_hybrid_parallel_env(self):
        """Parity: fleet_base.py:291."""
        hybrid = self._user_defined_strategy.hybrid_configs
        world = get_world_size()
        mp = max(1, hybrid.get('mp_degree', 1))
        pp = max(1, hybrid.get('pp_degree', 1))
        sharding = max(1, hybrid.get('sharding_degree', 1))
        dp = hybrid.get('dp_degree', -1)
        if dp in (-1, 0, None):
            dp = max(1, world // (mp * pp * sharding))
        self._topology = CommunicateTopology(
            hybrid_group_names=["data", "pipe", "sharding", "model"],
            dims=[dp, pp, sharding, mp])
        self._hcg = HybridCommunicateGroup(self._topology)
        return self._hcg

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_index(self):
        return lambda: self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ','.join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ','.join(eps) if to_string else eps

    def server_num(self):
        return self._role_maker.server_num()

    def barrier_worker(self):
        C.barrier()

    # -- server lifecycle (PS mode; see distributed/ps) -----------------------
    def init_worker(self, scopes=None):
        from ..runtime import the_one_ps
        the_one_ps.runtime().init_worker(self)

    def init_server(self, *args, **kwargs):
        from ..runtime import the_one_ps
        the_one_ps.runtime().init_server(self, *args, **kwargs)

    def run_server(self):
        from ..runtime import the_one_ps
        the_one_ps.runtime().run_server(self)

    def stop_worker(self):
        from ..runtime import the_one_ps
        the_one_ps.runtime().stop_worker(self)

    # -- model / optimizer wrapping -------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        """Parity: fleet_base.py:783."""
        if strategy is not None:
            self._user_defined_strategy = strategy
        self._user_defined_optimizer = optimizer
        if self._hcg is not None and (
                self._hcg.get_model_parallel_world_size() > 1
                or self._hcg.get_pipe_parallel_world_size() > 1
                or self._hcg.get_sharding_parallel_world_size() > 1):
            from ..meta_optimizers.dygraph_optimizer import (
                HybridParallelOptimizer)
            return HybridParallelOptimizer(optimizer, self._hcg,
                                           self._user_defined_strategy)
        return optimizer

    def distributed_model(self, model):
        """Parity: fleet_base.py:836 — dispatch on hcg parallel mode
        (:895-911)."""
        if self._hcg is None:
            from ...parallel import DataParallel
            return DataParallel(model)
        mode = self._hcg.get_parallel_mode()
        from ..meta_parallel import (TensorParallel, PipelineParallel,
                                     ShardingParallel)
        from ...parallel import DataParallel
        if mode == ParallelMode.SHARDING_PARALLEL:
            return ShardingParallel(model, self._hcg,
                                    strategy=self._user_defined_strategy)
        if mode == ParallelMode.DATA_PARALLEL:
            return DataParallel(model, group=self._hcg
                                .get_data_parallel_group())
        if mode == ParallelMode.TENSOR_PARALLEL:
            return TensorParallel(model, self._hcg,
                                  strategy=self._user_defined_strategy)
        if mode == ParallelMode.PIPELINE_PARALLEL:
            return PipelineParallel(model, self._hcg,
                                    strategy=self._user_defined_strategy)
        return model

    # -- static path -----------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """Parity: fleet_base.py minimize:1288 — static meta-optimizer
        chain via StrategyCompiler."""
        from .strategy_compiler import StrategyCompiler
        from ..meta_optimizers import resolve_meta_optimizers
        opt = self._user_defined_optimizer
        metas = resolve_meta_optimizers(self._user_defined_strategy, opt,
                                        self._role_maker)
        self.strategy_compiler = StrategyCompiler()
        ordered = self.strategy_compiler.generate_optimizer(
            loss, self._role_maker, opt, self._user_defined_strategy, metas)
        if ordered:
            return ordered[0].minimize(loss, startup_program,
                                       parameter_list, no_grad_set)
        return opt.minimize(loss)

    # -- save (parity: fleet_base.py:654-780 — delegates to the runtime:
    # PS path snapshots server tables via PsClient.save, collective path
    # saves the scope's persistables; a ZeRO-sharded program saves only
    # the parameters this rank owns) ------------------------------------------
    def _ps_client(self):
        from ..runtime import the_one_ps
        worker = the_one_ps.runtime()._worker
        return None if worker is None else worker.client

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        """Collective: every persistable var of `main_program` found in
        the scope → `<dirname>/__persistables__.npz` (only owned params
        for a sharded program; `<dirname>/__persistables__.rank<r>.npz`
        then). PS: additionally snapshots every server sparse table via
        PsClient.save. Returns {'vars': n, 'tables': [...]}."""
        if dirname is None:
            raise ValueError("fleet.save_persistables needs dirname")
        os.makedirs(dirname, exist_ok=True)
        out = {'vars': 0, 'tables': []}

        client = self._ps_client()
        if client is not None:
            from ..runtime.the_one_ps import table_configs
            for cfg in table_configs():
                tid = int(cfg['table_id'])
                client.save(tid, os.path.join(dirname,
                                              f"sparse_table_{tid}"))
                out['tables'].append(tid)

        from ....static.program import default_main_program, _ConstVar
        from ....static.executor import global_scope
        import jax
        prog = main_program or default_main_program()
        scope = global_scope()
        p2r = getattr(prog, '_sharding_param2rank', None)
        rank = getattr(prog, '_sharding_rank', 0)

        def _owner(name):
            """ZeRO ownership: a parameter's rank; optimizer-state vars
            (`<param>_<opt>_<state>_0`) follow their parameter — matched
            by LONGEST param prefix, so `w` never claims `w_big`'s state;
            other persistables (counters, LR state) belong to rank 0."""
            if name in p2r:
                return p2r[name]
            best = max((p for p in p2r if name.startswith(p + '_')),
                       key=len, default=None)
            return 0 if best is None else p2r[best]

        state = {}
        for v in prog.list_vars():
            if not getattr(v, 'persistable', False) \
                    or isinstance(v, _ConstVar) or v.name == '@LR':
                continue
            if p2r is not None and _owner(v.name) != rank:
                continue            # another shard owns this state
            arr = scope.find_var(v.name)
            if arr is not None:
                state[v.name] = np.asarray(jax.device_get(arr))
        # a save generation must not mix with leftovers from a previous
        # layout (load_persistables merges every matching file): an
        # unsharded save clears all rank files; a sharded save clears the
        # stale unsharded file, and rank 0 also clears rank files from a
        # previous HIGHER sharding degree. Removals tolerate races —
        # concurrently-saving ranks may target the same stale file.
        import glob
        import re
        stale = []
        if p2r is None:
            stale = glob.glob(os.path.join(dirname,
                                           '__persistables__.rank*.npz'))
        else:
            stale = glob.glob(os.path.join(dirname,
                                           '__persistables__.npz'))
            degree = max(p2r.values(), default=0) + 1
            if rank == 0:
                for f in glob.glob(os.path.join(
                        dirname, '__persistables__.rank*.npz')):
                    m = re.search(r'\.rank(\d+)\.npz$', f)
                    if m and int(m.group(1)) >= degree:
                        stale.append(f)
        for f in stale:
            try:
                os.remove(f)
            except FileNotFoundError:
                pass
        fname = '__persistables__.npz' if p2r is None \
            else f'__persistables__.rank{rank}.npz'
        np.savez(os.path.join(dirname, fname), **state)
        out['vars'] = len(state)
        return out

    def load_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        """Round-trip of save_persistables: stages every saved var (all
        rank files of a sharded save) back into the scope."""
        import glob
        import jax.numpy as jnp
        from ....static.executor import global_scope
        scope = global_scope()
        n = 0
        for f in sorted(glob.glob(os.path.join(
                dirname, '__persistables__*.npz'))):
            with np.load(f) as z:
                for name in z.files:
                    scope.set(name, jnp.asarray(z[name]))
                    n += 1
        return n

    def save(self, dirname, feed=None, fetch=None, **configs):
        """Parity: fleet_base.py save — with feed/fetch targets exports
        an inference model (pruned forward graph + params); otherwise
        saves program + persistables (paddle.static.save layout)."""
        from ....static.program import default_main_program
        from ....static import serialization as S
        os.makedirs(dirname, exist_ok=True)
        prog = configs.pop('main_program', None) or default_main_program()
        prefix = os.path.join(dirname, configs.pop('prefix', 'model'))
        if feed and fetch:
            return S.save_inference_model(prefix, feed, fetch,
                                          program=prog)
        return S.save(prog, prefix)

    def state_dict(self, mode=0, main_program=None):
        """Persistable name → Tensor for the main program's scope (PS
        sparse tables live server-side: snapshot them with
        save_persistables)."""
        from ....static.program import default_main_program, _ConstVar
        from ....static.executor import global_scope
        from ....core.tensor import Tensor
        prog = main_program or default_main_program()
        scope = global_scope()
        sd = {}
        for v in prog.list_vars():
            if not getattr(v, 'persistable', False) \
                    or isinstance(v, _ConstVar) or v.name == '@LR':
                continue
            arr = scope.find_var(v.name)
            if arr is not None:
                sd[v.name] = Tensor(arr)
        return sd

    def shrink(self, threshold=0.0):
        """PS mode: drop sparse rows with L2 norm below threshold on
        every server (reference: fleet.shrink → table shrink for stale
        features). Returns rows dropped, or 0 outside PS mode."""
        client = self._ps_client()
        if client is None:
            return 0
        from ..runtime.the_one_ps import table_configs
        total = 0
        for cfg in table_configs():
            total += client.shrink(int(cfg['table_id']), threshold)
        return total

    @property
    def util(self):
        return UtilBase()


class UtilBase:
    """Parity: fleet/base/util_factory.py UtilBase."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        return input

    def barrier(self, comm_world="worker"):
        C.barrier()

    def all_gather(self, input, comm_world="worker"):
        return [input]

    def get_file_shard(self, files):
        rank = get_rank()
        n = max(1, get_world_size())
        return files[rank::n]

    def print_on_rank(self, message, rank_id=0):
        if get_rank() == rank_id:
            print(message)
