"""Role makers.

Reference parity: fleet/base/role_maker.py — Gloo:35 (FS/HTTP KV rendezvous),
PaddleCloudRoleMaker:530 (PADDLE_* env parsing), UserDefinedRoleMaker:903.
On TPU the collective bootstrap is the PJRT/jax.distributed handshake; the
role maker keeps the env-parsing + role query surface.
"""
import os

from ...env import parallel_env


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False
        self._role = None
        self._current_id = -1

    def is_worker(self):
        raise NotImplementedError

    def is_server(self):
        raise NotImplementedError

    def is_first_worker(self):
        raise NotImplementedError

    def worker_num(self):
        raise NotImplementedError

    def worker_index(self):
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parity: role_maker.py:530."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._kwargs = kwargs
        self._generate_role()

    def _generate_role(self):
        env = parallel_env()
        self._current_id = env.rank
        self._worker_endpoints = env.trainer_endpoints
        self._trainers_num = env.world_size
        self._server_endpoints = [
            e for e in os.environ.get('PADDLE_PSERVERS_IP_PORT_LIST',
                                      '').split(',') if e]
        training_role = os.environ.get('TRAINING_ROLE', 'TRAINER')
        self._role = Role.SERVER if training_role == 'PSERVER' \
            else Role.WORKER
        self._role_is_generated = True

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_num(self):
        return self._trainers_num

    def server_num(self):
        return len(self._server_endpoints)

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def role_id(self):
        return self._current_id

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def _barrier(self, comm_world=None):
        pass

    def _all_gather(self, input, comm_world="worker"):
        return [input]

    def _all_reduce(self, input, mode="sum", comm_world="worker"):
        return input


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Parity: role_maker.py:903."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._init_kwargs = kwargs
        super().__init__(is_collective, **kwargs)

    def _generate_role(self):
        k = self._init_kwargs
        self._current_id = k.get('current_id', 0)
        self._role = k.get('role', Role.WORKER)
        self._worker_endpoints = k.get('worker_endpoints',
                                       ['127.0.0.1:6170'])
        self._server_endpoints = k.get('server_endpoints', [])
        self._trainers_num = k.get('worker_num',
                                   len(self._worker_endpoints))
        self._role_is_generated = True
