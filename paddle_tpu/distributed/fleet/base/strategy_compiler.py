"""Strategy compiler.

Reference parity: fleet/base/strategy_compiler.py StrategyCompiler:114 —
resolves enabled meta-optimizers into one valid application order
(maximum_path_len_algo:91 over declared inner-opt compatibility).
"""


def maximum_path_len_algo(optimizer_list):
    """Parity: strategy_compiler.py:91 — pick the longest chain of
    meta-optimizers where each accepts the next as its inner optimizer."""
    max_idx = 0
    max_len = 0
    candidates = []
    for opt in optimizer_list:
        local = [opt]
        for other in optimizer_list:
            if other is opt:
                continue
            names = [type(o).__name__ for o in local]
            if type(other).__name__ in getattr(local[-1],
                                               'meta_optimizers_white_list',
                                               []):
                local.append(other)
        candidates.append(local)
    for idx, c in enumerate(candidates):
        if len(c) > max_len:
            max_len = len(c)
            max_idx = idx
    if not candidates:
        return []
    chain = candidates[max_idx]
    for i in range(len(chain) - 1):
        chain[i]._update_inner_optimizer(chain[i + 1])
    return chain


class StrategyCompilerBase:
    pass


class StrategyCompiler(StrategyCompilerBase):
    """Parity: StrategyCompiler:114."""

    def __init__(self):
        self._meta_optimizers = []
        self._graph_optimizers = []
        self._valid_optimizer_list = None

    def _get_applied_meta_list(self):
        return [type(o).__name__ for o in (self._valid_optimizer_list or [])]

    def generate_optimizer(self, loss, role_maker, optimizer,
                           user_defined_strategy, meta_optimizers,
                           graph_optimizers=None):
        self._meta_optimizers = meta_optimizers
        if not meta_optimizers:
            self._valid_optimizer_list = []
            return []
        chain = maximum_path_len_algo(meta_optimizers)
        self._valid_optimizer_list = chain
        return chain
