"""N-D communication topology.

Reference parity: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology:36 (cartesian rank mesh over axes
["data","pipe","sharding","model"]) and HybridCommunicateGroup:117 (per-axis
comm groups, p2p pipe pairs get_p2p_groups:307). TPU-native: the same rank
math, but each axis additionally names a jax Mesh axis; groups carry
axis_name so collectives lower to XLA collectives on that axis. This unified
axis registry replaces the reference's per-meta-optimizer magic ring ids
(SURVEY.md A.3c).
"""
import collections
import itertools

import numpy as np

from ...collective import new_group
from ...env import get_rank, get_world_size
from ... import topology_runtime

# paddle axis name -> canonical short mesh-axis name
_MESH_AXIS = {'data': 'dp', 'pipe': 'pp', 'sharding': 'sharding',
              'model': 'mp', 'sep': 'sep'}


class CommunicateTopology:
    """Parity: topology.py:36."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = collections.namedtuple(
            'Coordinate', self._parallel_names)
        self._world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c)
                      for c in itertools.product(*ranges)]
        self._coord2rank = dict(zip(all_coords, range(len(all_coords))))
        self._rank2coord = dict(
            zip(self._coord2rank.values(), self._coord2rank.keys()))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        assert len(args) == len(self._dims)
        key = self.coordinate(**args)
        return self._coord2rank[key]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = [self._coord2rank[c] for c in self._coord2rank
                 if c[axis] == index]
        return sorted(ranks)

    def get_comm_list(self, axis_name):
        """All rank-groups along `axis_name` (one per setting of the other
        axes). Parity: topology.py get_comm_list."""
        other_axes = [n for n in self._parallel_names if n != axis_name]
        ranges = [range(self.get_dim(n)) for n in other_axes]
        all_result = []
        for coord in itertools.product(*ranges):
            fixed = dict(zip(other_axes, coord))
            group = []
            for i in range(self.get_dim(axis_name)):
                fixed[axis_name] = i
                group.append(self.get_rank(**fixed))
            all_result.append(group)
        return all_result

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    """Parity: topology.py:117. Builds per-axis Groups; on TPU each Group
    points at the mesh axis, and a single jax Mesh (dp, pp, sharding, mp) is
    registered for the SPMD engines."""

    def __init__(self, topology):
        self._topo = topology
        self.global_rank = get_rank()
        self._dp_degree = self._topo.get_dim('data')
        self._mp_degree = self._topo.get_dim('model')
        self._pp_degree = self._topo.get_dim('pipe')
        self._sharding_degree = self._topo.get_dim('sharding')

        self._data_parallel_id = self._get_parallel_id('data')
        self._model_parallel_id = self._get_parallel_id('model')
        self._sharding_parallel_id = self._get_parallel_id('sharding')
        self.stage_id = self._get_parallel_id('pipe')

        if self.global_rank >= self._topo.world_size():
            raise ValueError("rank outside topology")

        # build groups per axis (parity with _set_comm_group calls)
        self._dp_group, self._dp_comm_group = self._make_group('data')
        self._mp_group, self._mp_comm_group = self._make_group('model')
        self._pp_group, self._pp_comm_group = self._make_group('pipe')
        self._sharding_group, self._sharding_comm_group = \
            self._make_group('sharding')

        # check-group spanning dp+sharding (amp found_inf sync, parity
        # topology.py _set_check_group)
        self._check_group, self._check_comm_group = None, None

        # p2p neighbors for pipeline
        if self._pp_degree > 1:
            self.next_rank = self._topo.get_rank_from_stage(
                self.global_rank, pipe=(self.stage_id + 1) % self._pp_degree)
            self.prev_rank = self._topo.get_rank_from_stage(
                self.global_rank, pipe=(self.stage_id - 1) % self._pp_degree)
        else:
            self.next_rank = self.prev_rank = self.global_rank

        # register the jax mesh for SPMD engines (virtual or real devices)
        self._register_mesh()

    def _register_mesh(self):
        import jax
        names, sizes = [], []
        for pname in self._topo.get_hybrid_group_names():
            d = self._topo.get_dim(pname)
            names.append(_MESH_AXIS.get(pname, pname))
            sizes.append(d)
        total = int(np.prod(sizes))
        if total <= len(jax.devices()):
            topology_runtime.build_mesh(names, sizes)

    def _get_parallel_id(self, axis):
        coord = self._topo.get_coord(self.global_rank)
        return getattr(coord, axis)

    def _make_group(self, axis):
        parallel_lists = self._topo.get_comm_list(axis)
        mine = None
        for ranks in parallel_lists:
            if self.global_rank in ranks:
                mine = ranks
        g = new_group(ranks=mine or parallel_lists[0],
                      axis_name=_MESH_AXIS.get(axis, axis))
        return mine, g

    # -- parity accessors (topology.py names) -------------------------------
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 \
                and self._dp_degree == 1 and self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._mp_degree == 1 and self._pp_degree == 1:
            return ParallelMode.DATA_PARALLEL
        if self._mp_degree > 1 and self._pp_degree == 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # dp
    def get_data_parallel_rank(self):
        return self._data_parallel_id

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_comm_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group[0] if self._dp_group else 0

    # mp
    def get_model_parallel_rank(self):
        return self._model_parallel_id

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_comm_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group[0] if self._mp_group else 0

    # pp
    def get_stage_id(self):
        return self.stage_id

    def get_pipe_parallel_rank(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_comm_group

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self._pp_degree - 1

    def get_p2p_groups(self):
        return (self.prev_rank, self.next_rank)

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_parallel_id

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_comm_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group[0] if self._sharding_group else 0

    def get_check_parallel_group(self):
        return self._check_comm_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)


class ParallelMode:
    """Parity: paddle.distributed.ParallelMode."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
