"""DistributedStrategy.

Reference parity: fleet/base/distributed_strategy.py:105 over
framework/distributed_strategy.proto:159 — the strategy object with typed
config sub-dicts: amp, recompute, pipeline, sharding, tensor_parallel,
hybrid_configs, gradient_merge, localsgd, lamb, lars, dgc, a_sync, asp,
elastic... Protobuf is replaced by plain dataclass-style dicts with the same
field names so user code ports unchanged; save_to_prototxt serializes JSON.
"""
import copy
import json


_DEFAULTS = {
    'amp': False,
    'amp_configs': {
        'init_loss_scaling': 32768.0, 'incr_every_n_steps': 1000,
        'decr_every_n_nan_or_inf': 2, 'incr_ratio': 2.0, 'decr_ratio': 0.5,
        'use_dynamic_loss_scaling': True, 'custom_white_list': [],
        'custom_black_list': [], 'custom_black_varnames': [],
        'use_pure_fp16': False, 'use_fp16_guard': True, 'dtype': 'bfloat16'},
    'recompute': False,
    # 'policy' picks the tuned trace-level remat policy for the compiled
    # engines (docs/performance.md#remat-policy): None = engine default,
    # or 'none' | 'full' | 'attn_mlp_boundaries' | 'dots'
    # (PTPU_REMAT_POLICY env twin; engine kwarg `remat_policy` wins)
    'recompute_configs': {'checkpoints': [], 'enable_offload': False,
                          'checkpoint_shape': [], 'policy': None},
    'pipeline': False,
    'pipeline_configs': {'micro_batch_size': 1, 'accumulate_steps': 1,
                         'schedule_mode': '1F1B', 'p2p_cache_shape': True},
    'sharding': False,
    'sharding_configs': {
        'sharding_segment_strategy': 'segment_broadcast_MB',
        'segment_broadcast_MB': 32.0, 'segment_anchors': None,
        'sharding_degree': 8, 'mp_degree': 1, 'pp_degree': 1, 'dp_degree': 1,
        'hybrid_dp': False, 'gradient_merge_acc_step': 1,
        'optimize_offload': False, 'stage': 1,
        'pp_allreduce_in_optimize': False, 'optimize_cast': False,
        # communication/compute overlap for the bucketed SPMD engines
        # (ISSUE 10, docs/performance.md#comm-overlap): layer-grouped
        # buckets + eager reduce-scatter + deferred/prefetched param
        # all-gather; 'comm_overlap_prefetch' bounds the param groups
        # gathered ahead of first use; 'comm_chunk' (elements, 0=off)
        # decomposes oversized bucket collectives into schedulable
        # pieces (PTPU_COMM_OVERLAP / PTPU_COMM_PREFETCH /
        # PTPU_COMM_CHUNK env twins)
        'comm_overlap': False, 'comm_overlap_prefetch': 2,
        'comm_chunk': 0},
    'tensor_parallel': False,
    # 'sequence_parallel' shards the LayerNorm/dropout/residual
    # activations between mp regions along the sequence dim
    # (Megatron-style RS/AG in place of the row allreduce —
    # docs/performance.md#sequence-parallel-activations;
    # PTPU_SEQUENCE_PARALLEL env twin; engine kwarg wins)
    'tensor_parallel_configs': {'tensor_parallel_degree': 1,
                                'tensor_init_seed': -1,
                                'sequence_parallel': False},
    'hybrid_configs': {'dp_degree': -1, 'mp_degree': 1, 'pp_degree': 1,
                       'sharding_degree': 1, 'sep_degree': 1},
    'gradient_merge': False,
    'gradient_merge_configs': {'k_steps': 1, 'avg': True},
    'localsgd': False,
    'localsgd_configs': {'k_steps': 1, 'begin_step': 1},
    'adaptive_localsgd': False,
    'adaptive_localsgd_configs': {'init_k_steps': 1, 'begin_step': 1},
    'dgc': False,
    'dgc_configs': {'rampup_begin_step': 0, 'rampup_step': 1,
                    'sparsity': [0.999]},
    'lars': False,
    'lars_configs': {'lars_coeff': 0.001, 'lars_weight_decay': 0.0005,
                     'epsilon': 0, 'exclude_from_weight_decay': []},
    'lamb': False,
    'lamb_configs': {'lamb_weight_decay': 0.01,
                     'exclude_from_weight_decay': []},
    'a_sync': False,
    'a_sync_configs': {'k_steps': -1, 'max_merge_var_num': 1,
                       'send_queue_size': 16,
                       'independent_recv_thread': False,
                       'min_send_grad_num_before_recv': 1,
                       'thread_pool_size': 1, 'send_wait_times': 1,
                       'runtime_split_send_recv': False, 'launch_barrier':
                       True, 'heter_worker_device_guard': 'cpu',
                       'lr_decay_steps': 10, 'use_ps_gpu': 0},
    'asp': False,
    'fp16_allreduce': False,
    'sync_nccl_allreduce': True,
    'sync_batch_norm': False,
    'fuse_all_reduce_ops': True,
    # gradient-collective wire dtype for the bucketed SPMD engines:
    # None = native; 'bfloat16' = compressed wire with fp32 accumulate;
    # 'int8' = block-scaled int8 wire (per-block abs-max fp32 scales
    # travel beside the payload) with fp32 accumulate (EQuARX-style;
    # see docs/performance.md)
    'comm_dtype': None,
    'fuse_grad_size_in_MB': 32,
    'fuse_grad_size_in_TFLOPS': 50,
    'nccl_comm_num': 1,
    'use_hierarchical_allreduce': False,
    'hierarchical_allreduce_inter_nranks': 1,
    'find_unused_parameters': False,
    'without_graph_optimization': False,
    'elastic': False,
    'auto': False,
    'semi_auto': False,
    'heter_ccl_mode': False,
    'cudnn_exhaustive_search': False,
    'conv_workspace_size_limit': 512,
    'cudnn_batchnorm_spatial_persistent': False,
    'last_comm_group_size_MB': 1.0,
    'gradient_scale_configs': {'scale_strategy': 'avg'},
}


class DistributedStrategy:
    """Parity: DistributedStrategy:105. Attribute surface mirrors the proto
    fields; unknown assignments raise to catch typos like the original's
    check."""

    def __init__(self):
        object.__setattr__(self, '_conf', copy.deepcopy(_DEFAULTS))

    def __getattr__(self, name):
        conf = object.__getattribute__(self, '_conf')
        if name in conf:
            return copy.copy(conf[name])
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        conf = object.__getattribute__(self, '_conf')
        if name not in conf:
            raise AttributeError(f"DistributedStrategy has no field {name!r}")
        if name.endswith('_configs'):
            merged = dict(conf[name])
            for k, v in value.items():
                if k not in merged:
                    raise ValueError(
                        f"{name} has no config key {k!r} "
                        f"(valid: {sorted(merged)})")
                merged[k] = v
            conf[name] = merged
        else:
            conf[name] = value

    # -- (de)serialization (parity: save_to_prototxt:146) --------------------
    def save_to_prototxt(self, output):
        with open(output, 'w') as f:
            json.dump(object.__getattribute__(self, '_conf'), f, indent=2)

    def load_from_prototxt(self, pb_file):
        with open(pb_file) as f:
            loaded = json.load(f)
        conf = object.__getattribute__(self, '_conf')
        conf.update(loaded)

    def __repr__(self):
        conf = object.__getattribute__(self, '_conf')
        on = [k for k, v in conf.items() if v is True]
        return f"DistributedStrategy(enabled={on})"
