"""fleet data generators — the user-side half of the PS ingestion pipe.

Reference parity: fleet/data_generator/data_generator.py:20 (DataGenerator,
MultiSlotDataGenerator:282, MultiSlotStringDataGenerator:240). A user
subclasses `generate_sample(line)` (and optionally `generate_batch`), then
the trainer runs the subclass as the dataset's `pipe_command`: raw file
lines stream in on stdin, and count-prefixed MultiSlot text
(`<n> v1 .. vn  <m> u1 .. um ...`, one sample per line) streams out on
stdout — byte-compatible with the reference wire protocol, so existing
pipe scripts port unchanged.

TPU-native note: the native feed (csrc/data_feed.cc) assembles FIXED-width
dense batches (no LoD); the dataset layer bridges the count-prefixed pipe
output to that layout and enforces that each slot's count matches the
declared width (dataset.py `_multislot_to_dense`).
"""
import sys

__all__ = []


class DataGenerator:
    """Base class: subclass and override `generate_sample` (per raw
    line) and optionally `generate_batch` (whole-batch post-processing,
    e.g. padding). Both must return a zero-arg callable yielding
    `[(slot_name, [values...]), ...]` samples."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        """Batch size used to group samples before `generate_batch`."""
        self.batch_size_ = int(batch_size)

    # -- pipe entry points ---------------------------------------------------
    def run_from_stdin(self):
        """The pipe_command role: raw lines on stdin -> protocol lines
        on stdout (reference run_from_stdin)."""
        self._run(sys.stdin, sys.stdout)

    def run_from_memory(self):
        """Debug/bench entry: generate_sample(None) drives the stream
        (reference run_from_memory)."""
        batch = []
        for sample in self.generate_sample(None)():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                self._flush(batch, sys.stdout)
                batch = []
        if batch:
            self._flush(batch, sys.stdout)

    def _run(self, lines, out):
        batch = []
        for line in lines:
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush(batch, out)
                    batch = []
        if batch:
            self._flush(batch, out)

    def _flush(self, batch, out):
        for sample in self.generate_batch(batch)():
            out.write(self._gen_str(sample))

    # -- user hooks ----------------------------------------------------------
    def generate_sample(self, line):
        raise NotImplementedError(
            "override generate_sample(line) to return a zero-arg "
            "callable yielding [(slot_name, [values...]), ...] "
            "(reference data_generator.py:173)")

    def generate_batch(self, samples):
        def passthrough():
            for s in samples:
                yield s
        return passthrough

    def _gen_str(self, sample):
        raise NotImplementedError(
            "use MultiSlotDataGenerator (int/float slots) or "
            "MultiSlotStringDataGenerator (string feasigns)")

    # shared serializer: "<count> v1 .. vn" per slot, space-joined
    def _serialize(self, sample, to_str):
        if isinstance(sample, zip):
            sample = list(sample)
        if not isinstance(sample, (list, tuple)):
            raise ValueError(
                "a generated sample must be a list/tuple of "
                "(name, [values...]) pairs, got %r" % type(sample))
        parts = []
        for name, values in sample:
            if not isinstance(values, (list, tuple)) or not values:
                raise ValueError(
                    f"slot '{name}': values must be a non-empty list "
                    "(pad in generate_sample/generate_batch)")
            parts.append(str(len(values)))
            parts.extend(to_str(name, v) for v in values)
        return ' '.join(parts) + '\n'


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: values are int (uint64 slot) or float (float
    slot); the slot kind is latched per name across the stream, like
    the reference's running _proto_info."""

    def _gen_str(self, sample):
        if isinstance(sample, zip):
            sample = list(sample)
        if not isinstance(sample, (list, tuple)):
            raise ValueError(
                "a generated sample must be a list/tuple of "
                "(name, [values...]) pairs")
        if self._proto_info is None:
            self._proto_info = [(name, 'uint64') for name, _ in sample]
        elif len(sample) != len(self._proto_info):
            raise ValueError(
                f"inconsistent slot count: expected "
                f"{len(self._proto_info)}, got {len(sample)}")

        def to_str(name, v):
            idx = next(i for i, (n, _) in enumerate(self._proto_info)
                       if n == name)
            if isinstance(v, float):
                self._proto_info[idx] = (name, 'float')
            elif not isinstance(v, int):
                raise ValueError(
                    f"slot '{name}': values must be int or float, "
                    f"got {type(v)}")
            return str(v)
        for i, (name, _) in enumerate(sample):
            if name != self._proto_info[i][0]:
                raise ValueError(
                    f"slot name mismatch at {i}: expected "
                    f"'{self._proto_info[i][0]}', got '{name}'")
        return self._serialize(sample, to_str)


class MultiSlotStringDataGenerator(DataGenerator):
    """String feasigns: values pass through verbatim (reference
    MultiSlotStringDataGenerator — no proto typing)."""

    def _gen_str(self, sample):
        return self._serialize(sample, lambda name, v: str(v))
