from .data_generator import (DataGenerator, MultiSlotDataGenerator,
                             MultiSlotStringDataGenerator)

__all__ = ['DataGenerator', 'MultiSlotDataGenerator',
           'MultiSlotStringDataGenerator']
