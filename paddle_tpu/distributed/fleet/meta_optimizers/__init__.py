"""Static-graph meta-optimizers.

Reference parity: fleet/meta_optimizers/* (P18) — strategy-driven program
rewriters chained by StrategyCompiler. On TPU several reference rewrites are
subsumed by XLA/GSPMD (multi-stream scheduling, fusion, allreduce insertion
for annotated shardings); each class below documents what still rewrites the
Program versus what becomes an execution-time annotation.
"""
import numpy as np

from ..base.distributed_strategy import DistributedStrategy


class MetaOptimizerBase:
    """Parity: meta_optimizer_base.py MetaOptimizerBase."""

    meta_optimizers_white_list = []
    meta_optimizers_black_list = []

    def __init__(self, optimizer):
        self.inner_opt = optimizer
        self.user_defined_strategy = None
        self.role_maker = None

    def _set_basic_info(self, loss, role_maker, user_defined_optimizer,
                        user_defined_strategy):
        self.loss = loss
        self.role_maker = role_maker
        self.user_defined_optimizer = user_defined_optimizer
        self.user_defined_strategy = user_defined_strategy

    def _update_inner_optimizer(self, optimizer):
        self.inner_opt = optimizer

    def _can_apply(self):
        return False

    def _is_graph_out(self):
        return False

    def _disable_strategy(self, dist_strategy):
        pass

    def _enable_strategy(self, dist_strategy, context=None):
        pass

    def _nranks(self):
        """Worker count from the role maker (1 when unset/unreachable)."""
        if self.role_maker is not None:
            try:
                return int(self.role_maker.worker_num())
            except Exception:
                return 1
        return 1

    def apply_gradients(self, params_grads):
        return self.inner_opt.apply_gradients(params_grads)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from ....static import append_backward
        return append_backward(loss, parameter_list)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.minimize(loss, startup_program)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.minimize_impl(loss, startup_program, parameter_list,
                                  no_grad_set)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)


class RawProgramOptimizer(MetaOptimizerBase):
    """Parity: raw_program_optimizer.py:28 — REAL dp grad exchange: the
    loss cotangent is pre-scaled by 1/nranks (:_insert_loss_grad_ops) and
    one `c_allreduce_sum` op is inserted per parameter gradient before
    the optimize ops (:158 _insert_allreduce_ops). Single-process replay
    runs them as identities; multi-rank semantics execute through the
    collective resolver (MultiRankShardingSimulator / fleetrun)."""

    meta_optimizers_white_list = ['RecomputeOptimizer', 'AMPOptimizer']

    def _can_apply(self):
        return bool(self.user_defined_strategy.without_graph_optimization)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.meta_passes import insert_dp_grad_sync
        prog = loss.block.program
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        insert_dp_grad_sync(prog, self._nranks())
        return out


class AMPOptimizer(MetaOptimizerBase):
    """Parity: amp_optimizer.py:20 — static AMP via REAL cast-insertion
    (fp16_utils.rewrite_program:484) over the recorded forward ops, run
    BEFORE append_backward so grads differentiate through the casts. The
    low-precision dtype is bf16 (MXU-native)."""

    meta_optimizers_white_list = ['LarsOptimizer', 'LambOptimizer',
                                  'RecomputeOptimizer',
                                  'GradientMergeOptimizer',
                                  'RawProgramOptimizer']

    def _can_apply(self):
        return bool(self.user_defined_strategy.amp)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.amp_pass import (rewrite_program_amp,
                                         AutoMixedPrecisionLists)
        prog = loss.block.program
        cfg = dict(self.user_defined_strategy.amp_configs)
        prog._amp = cfg
        lists = AutoMixedPrecisionLists(
            cfg.get('custom_white_list'), cfg.get('custom_black_list'),
            cfg.get('custom_black_varnames'))
        rewrite_program_amp(prog, lists)
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)


class RecomputeOptimizer(MetaOptimizerBase):
    """Parity: recompute_optimizer.py → fluid RecomputeOptimizer:5402
    (_append_backward_ops_with_checkpoints_). REAL segment-recompute
    rewrite: forward intermediates between checkpoints are dropped from
    the backward's live set and recomputed (behind an
    optimization_barrier so XLA cannot CSE the copy away) right before
    their grad consumers — see static/recompute_pass.py."""

    meta_optimizers_white_list = ['LarsOptimizer', 'LambOptimizer',
                                  'GradientMergeOptimizer',
                                  'RawProgramOptimizer']

    def _can_apply(self):
        return bool(self.user_defined_strategy.recompute)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.recompute_pass import rewrite_recompute
        prog = loss.block.program
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        rewrite_recompute(prog, list(
            self.user_defined_strategy.recompute_configs['checkpoints']))
        return out


class GradientMergeOptimizer(MetaOptimizerBase):
    """Parity: gradient_merge_optimizer.py → fluid GradientMergeOptimizer:
    6255. REAL rewrite: per-grad persistable `@GradientMerge`
    accumulators, a step counter, and the Optimize-role ops moved into a
    conditional_block sub-block firing every k-th step on the averaged
    accumulators (then zeroed) — see static/meta_passes.py."""

    meta_optimizers_white_list = []

    def _can_apply(self):
        return bool(self.user_defined_strategy.gradient_merge)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.meta_passes import apply_gradient_merge
        prog = loss.block.program
        cfg = self.user_defined_strategy.gradient_merge_configs
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        apply_gradient_merge(prog, cfg['k_steps'],
                             avg=bool(cfg.get('avg', True)))
        return out


class LocalSGDOptimizer(MetaOptimizerBase):
    """Parity: localsgd_optimizer.py:27,63-79. REAL rewrite: ranks train
    independently; a step counter + gate and per-parameter
    c_allreduce_sum/blend ops synchronize every parameter to the
    cross-rank average on every k-th step (static/meta_passes.py
    apply_localsgd — arithmetic gate instead of the reference's cond:
    lockstep XLA prefers a static collective schedule)."""

    def _can_apply(self):
        return bool(self.user_defined_strategy.localsgd)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.meta_passes import apply_localsgd
        prog = loss.block.program
        k = self.user_defined_strategy.localsgd_configs['k_steps']
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        apply_localsgd(prog, k, self._nranks())
        return out


class LarsOptimizer(MetaOptimizerBase):
    """Parity: lars_optimizer.py — swap inner Momentum for Lars."""

    def _can_apply(self):
        from ....optimizer import Momentum
        return bool(self.user_defined_strategy.lars) and \
            isinstance(self.user_defined_optimizer, Momentum)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import Lars
        cfg = self.user_defined_strategy.lars_configs
        inner = self.user_defined_optimizer
        opt = Lars(learning_rate=inner._learning_rate,
                   momentum=inner._momentum,
                   lars_coeff=cfg['lars_coeff'],
                   lars_weight_decay=cfg['lars_weight_decay'],
                   parameters=inner._parameter_list,
                   epsilon=cfg['epsilon'])
        return opt.minimize(loss, startup_program, parameter_list,
                            no_grad_set)


class LambOptimizer(MetaOptimizerBase):
    """Parity: lamb_optimizer.py — swap inner Adam for Lamb."""

    def _can_apply(self):
        from ....optimizer import Adam
        return bool(self.user_defined_strategy.lamb) and \
            isinstance(self.user_defined_optimizer, Adam)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import Lamb
        cfg = self.user_defined_strategy.lamb_configs
        inner = self.user_defined_optimizer
        opt = Lamb(learning_rate=inner._learning_rate,
                   lamb_weight_decay=cfg['lamb_weight_decay'],
                   parameters=inner._parameter_list)
        return opt.minimize(loss, startup_program, parameter_list,
                            no_grad_set)


class PipelineOptimizer(MetaOptimizerBase):
    """Parity: fleet pipeline_optimizer.py:28 over fluid
    PipelineOptimizer:4135 (the program splitter). After the inner minimize
    records backward + optimize ops, the program is REALLY split: one
    program per stage keyed on op_device, send_v2/recv_v2 at boundaries
    (static/pipeline_pass.py). The SPMD engine
    (meta_parallel/spmd_pipeline.py) remains the multi-chip fast path."""

    def _can_apply(self):
        return bool(self.user_defined_strategy.pipeline)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.pipeline_pass import split_program, _stage_of
        prog = loss.block.program
        prog._pipeline_opt = dict(
            self.user_defined_strategy.pipeline_configs)
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        # num_stages = highest device_guard stage annotation + 1
        stages = [s for op in prog.global_block().ops
                  for s in [_stage_of(op.op_device, 1 << 30)]
                  if s is not None]
        if stages and max(stages) > 0:
            progs, rings = split_program(prog, max(stages) + 1)
            prog._pipeline_stage_programs = progs
            prog._pipeline_pair_rings = rings
        return out


class TensorParallelOptimizer(MetaOptimizerBase):
    """Parity: tensor_parallel_optimizer.py — validates nranks divides by
    mp_degree, records the mp/dp ring split, and (when nranks >
    mp_degree) REALLY transpiles the main program for the outer data
    parallelism: loss-cotangent scale by 1/dp_degree + per-grad
    c_allreduce_sum on the dp ring (reference _transpile_main_program /
    _insert_allreduce_ops). The mp collectives themselves are the
    recorded c_* ops inside the model (collective.py split/_c_embedding/
    _c_softmax_with_cross_entropy)."""

    DP_RING = 2              # reference ring convention: mp=0 global=1 dp=2

    def _can_apply(self):
        return bool(self.user_defined_strategy.tensor_parallel)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.meta_passes import insert_dp_grad_sync
        prog = loss.block.program
        mp = int(self.user_defined_strategy
                 .tensor_parallel_configs['tensor_parallel_degree'])
        nranks = max(self._nranks(), 1)
        if nranks % mp != 0:
            raise ValueError(
                f"tensor_parallel_degree={mp} must divide the worker "
                f"count {nranks}")
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        prog._mp_degree = mp
        if nranks > mp:
            insert_dp_grad_sync(prog, nranks // mp, ring_id=self.DP_RING)
        return out


class ShardingOptimizer(MetaOptimizerBase):
    """Parity: sharding_optimizer.py:43 (ZeRO-1/2). After the inner
    minimize records backward + optimize ops, the program is REALLY
    rewritten for this rank (static/sharding_pass.py): per-grad
    c_reduce_sum/c_allreduce_sum, non-owned optimize ops + state pruned,
    c_broadcast of updated params. On a real mesh the same semantics run
    through the hybrid SPMD engine (GSPMD reduce-scatter/all-gather)."""

    def _can_apply(self):
        return bool(self.user_defined_strategy.sharding)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.sharding_pass import shard_program
        prog = loss.block.program
        cfg = dict(self.user_defined_strategy.sharding_configs)
        prog._sharding = cfg
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        degree = int(cfg.get('sharding_degree', 1) or 1)
        if degree > 1:
            rank = 0
            if self.role_maker is not None:
                try:
                    rank = self.role_maker._worker_index()
                except Exception:
                    rank = 0
            shard_program(prog, rank % degree, degree,
                          stage=int(cfg.get('stage', 2) or 2))
        return out


class DGCOptimizer(MetaOptimizerBase):
    """Parity: dgc_optimizer.py:22 — swaps Momentum for
    DGCMomentumOptimizer (top-k grad compression with local residual
    accumulation). DCN-relevant on TPU (ICI is fast)."""

    def _can_apply(self):
        from ....optimizer import Momentum
        return bool(self.user_defined_strategy.dgc) and \
            isinstance(self.user_defined_optimizer, Momentum)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....optimizer import DGCMomentumOptimizer
        cfg = self.user_defined_strategy.dgc_configs
        inner = self.user_defined_optimizer
        opt = DGCMomentumOptimizer(
            learning_rate=inner._learning_rate,
            momentum=inner._momentum,
            rampup_begin_step=cfg.get('rampup_begin_step', 0),
            rampup_step=cfg.get('rampup_step', 1),
            sparsity=cfg.get('sparsity', [0.999]),
            parameters=inner._parameter_list,
            use_nesterov=inner._use_nesterov,
            weight_decay=inner._weight_decay,
            grad_clip=inner._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list,
                            no_grad_set)


class FP16AllReduceOptimizer(MetaOptimizerBase):
    """Parity: fp16_allreduce_optimizer.py — each parameter gradient is
    rounded through bf16 immediately after its producing backward op,
    BEFORE any collective consumes it, so the replay computes exactly the
    numerics of a half-width exchange (each rank's contribution rounded,
    then summed). The down/up pair is one fused op — XLA folds it into
    the collective's input; the eager DataParallel path puts literal bf16
    buckets on the wire (parallel.py)."""

    # compose ON TOP of the rewrites that insert the collectives — the
    # casts must see them to land before the exchange
    meta_optimizers_white_list = ['ShardingOptimizer', 'RecomputeOptimizer',
                                  'AMPOptimizer', 'DGCOptimizer']

    def _can_apply(self):
        return bool(self.user_defined_strategy.fp16_allreduce)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        import jax.numpy as jnp
        from ....static.program import Operator, OpRole
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        prog = loss.block.program
        block = prog.global_block()
        grad_names = {g for g in prog._grad_map.values()
                      if g in block.vars}
        COLLECTIVES = {'c_allreduce_sum', 'c_reduce_sum'}

        def _make_cast(gname):
            return Operator(
                'cast_fp16_allreduce',
                lambda g: g.astype(jnp.bfloat16).astype(g.dtype),
                [gname], [gname], {'wire_dtype': 'bfloat16'},
                op_role=OpRole.Backward)

        # insertion point per grad: immediately BEFORE the first
        # collective consuming it (the exchange); with no collective in
        # the program, before the first Optimize consumer
        inserts = []            # (position, gname)
        for gname in grad_names:
            pos = None
            for i, op in enumerate(block.ops):
                if op.type in COLLECTIVES and gname in op.input_names:
                    pos = i
                    break
            if pos is None:
                for i, op in enumerate(block.ops):
                    if (op.op_role & OpRole.Optimize)                             and gname in op.input_names:
                        pos = i
                        break
            if pos is not None:
                inserts.append((pos, gname))
        for pos, gname in sorted(inserts, reverse=True):
            block.ops.insert(pos, _make_cast(gname))
        return out


class ASPOptimizer(MetaOptimizerBase):
    """Parity: asp_optimizer.py — 2:4 structured sparsity masks."""

    def _can_apply(self):
        return bool(self.user_defined_strategy.asp)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....incubate import asp as asp_mod
        return asp_mod.decorate(self.inner_opt).minimize(
            loss, startup_program, parameter_list, no_grad_set)


class ParameterServerOptimizer(MetaOptimizerBase):
    """Parity: parameter_server_optimizer.py _build_trainer_programs →
    trainer_pass append_send_ops. REAL worker-side rewrite: after the
    inner minimize records backward ops, every `distributed_lookup`
    output's cotangent gains a `distributed_push` op carrying it to the
    parameter server (static/heter_pass.py wire_sparse_grads — the
    sparse-gradient send half of the PS split); dense params keep local
    optimize ops per the a_sync geo pattern."""

    def _can_apply(self):
        return bool(self.user_defined_strategy.a_sync)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.heter_pass import wire_sparse_grads
        prog = loss.block.program
        out = self.inner_opt.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        prog._ps_mode = dict(self.user_defined_strategy.a_sync_configs)
        prog._ps_push_count = wire_sparse_grads(prog)
        return out


_ALL_META_OPTIMIZERS = [AMPOptimizer, RecomputeOptimizer,
                        GradientMergeOptimizer, LocalSGDOptimizer,
                        LarsOptimizer, LambOptimizer, PipelineOptimizer,
                        TensorParallelOptimizer, ShardingOptimizer,
                        DGCOptimizer, FP16AllReduceOptimizer, ASPOptimizer,
                        ParameterServerOptimizer, RawProgramOptimizer]


def resolve_meta_optimizers(strategy, optimizer, role_maker, loss=None):
    """Parity: MetaOptimizerFactory._get_valid_meta_optimizers +
    fleet_base.minimize's _can_apply filtering."""
    out = []
    for cls in _ALL_META_OPTIMIZERS:
        m = cls(optimizer)
        m._set_basic_info(loss, role_maker, optimizer, strategy)
        if m._can_apply():
            out.append(m)
    return out
