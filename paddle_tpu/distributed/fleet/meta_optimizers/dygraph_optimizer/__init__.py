"""Dygraph hybrid-parallel optimizers.

Reference parity: fleet/meta_optimizers/dygraph_optimizer —
HybridParallelOptimizer (hybrid_parallel_optimizer.py:89, TP/PP-aware global
clip :32), DygraphShardingOptimizer (dygraph_sharding_optimizer.py:27, ZeRO-1
greedy size-balanced partitioning :90), HybridParallelGradScaler.
"""
import numpy as np
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn.clip import ClipGradByGlobalNorm
from .... import collective as C
from ...utils.hybrid_parallel_util import (fused_allreduce_gradients,
                                           sharding_reduce_gradients)


class HybridParallelClipGrad:
    """Parity: hybrid_parallel_optimizer.py:32 — global-norm clip where each
    rank holds only a shard: partial square-sums are psum'd across the mp(+pp,
    +sharding) axes before the global norm. Outside SPMD (single controller,
    full params visible) the plain global norm is already correct."""

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        sq_dist = 0.0
        sq_rep = 0.0
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                continue
            s = jnp.sum(g.data.astype(jnp.float32) ** 2)
            if getattr(p, 'is_distributed', False):
                sq_dist = sq_dist + s
            else:
                sq_rep = sq_rep + s
        if C.in_spmd_region():
            t = Tensor(jnp.asarray(sq_dist))
            C.all_reduce(t, group=self._hcg.get_model_parallel_group())
            sq_dist = t.data
        gn = jnp.sqrt(sq_dist + sq_rep)
        factor = self._clip.clip_norm / jnp.maximum(gn, self._clip.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor)
                                  .astype(g.dtype))))
        return out


class HybridParallelOptimizer:
    """Parity: hybrid_parallel_optimizer.py:89 — wraps the inner optimizer,
    swaps the clip for the mesh-aware one, and syncs dp/sharding grads before
    stepping."""

    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._need_dp = hcg.get_data_parallel_world_size() > 1
        self._sharding = hcg.get_sharding_parallel_world_size() > 1
        if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)

    @property
    def _parameter_list(self):
        return self._inner_opt._parameter_list

    @property
    def _learning_rate(self):
        return self._inner_opt._learning_rate

    def step(self):
        params = self._inner_opt._parameter_list or []
        if self._sharding:
            sharding_reduce_gradients(list(params), self._hcg)
        elif self._need_dp:
            fused_allreduce_gradients(list(params), self._hcg)
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        self._inner_opt.set_lr(v)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        self._inner_opt.set_state_dict(sd)

    def functional_apply(self, *args, **kwargs):
        return self._inner_opt.functional_apply(*args, **kwargs)

    def init_state(self, p):
        return self._inner_opt.init_state(p)

    def update(self, *args):
        return self._inner_opt.update(*args)

    def __getattr__(self, item):
        return getattr(self.__dict__['_inner_opt'], item)


class DygraphShardingOptimizer:
    """Parity: dygraph_sharding_optimizer.py:27 — ZeRO-1: partition params
    across the sharding group by greedy size balancing
    (_partition_parameters:90); each rank updates only its shard and
    broadcasts updated params. On the single-controller SPMD path the same
    partitioning drives reduce-scatter + all-gather placement."""

    def __init__(self, hcg, user_defined_strategy, params, inner_optimizer_class,
                 **inner_kw):
        self._hcg = hcg
        self._sharding_world = hcg.get_sharding_parallel_world_size()
        self._sharding_rank = hcg.get_sharding_parallel_rank()
        self._parameter_list = list(params)
        self._rank2params = self._partition_parameters()
        local = self._rank2params[self._sharding_rank]
        self._inner_opt = inner_optimizer_class(
            parameters=local, **inner_kw)

    def _partition_parameters(self):
        """Parity: _partition_parameters:90 — greedy smallest-bucket."""
        mapping = {i: [] for i in range(self._sharding_world)}
        sizes = [0.0] * self._sharding_world
        for param in sorted(self._parameter_list,
                            key=lambda p: -int(np.prod(p.shape or [1]))):
            rank = int(np.argmin(sizes))
            mapping[rank].append(param)
            numel = int(np.prod(param.shape or [1]))
            sizes[rank] += numel
        return mapping

    def param_to_rank(self, param):
        for rank, plist in self._rank2params.items():
            if any(p is param for p in plist):
                return rank
        return -1

    def reduce_gradients(self, parameter_list, hcg):
        sharding_reduce_gradients(parameter_list, hcg)

    def step(self):
        self.reduce_gradients(self._parameter_list, self._hcg)
        self._inner_opt.step()
        self._broadcast_params()

    def _broadcast_params(self):
        if not C.in_spmd_region():
            return
        group = self._hcg.get_sharding_parallel_group()
        for rank, params in self._rank2params.items():
            for p in params:
                C.broadcast(p, src=rank, group=group)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner_opt.get_lr()

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        self._inner_opt.set_state_dict(sd)

    def __getattr__(self, item):
        return getattr(self.__dict__['_inner_opt'], item)


class HybridParallelGradScaler:
    """Parity: hybrid_parallel_gradscaler.py — found_inf allreduced across
    the whole mesh (A.8)."""

    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, item):
        return getattr(self.__dict__['_scaler'], item)

    def scale(self, var):
        return self._scaler.scale(var)

    def step(self, optimizer):
        self._scaler.unscale_(optimizer
                              if not hasattr(optimizer, '_inner_opt')
                              else optimizer._inner_opt)
        if C.in_spmd_region():
            flag = Tensor(jnp.asarray(
                1.0 if self._scaler._found_inf else 0.0))
            C.all_reduce(flag, op=C.ReduceOp.MAX)
            self._scaler._found_inf = bool(np.asarray(flag.data) > 0)
        self._scaler.step(optimizer if not hasattr(optimizer, '_inner_opt')
                          else optimizer._inner_opt)
