"""Global mesh registry.

Reference parity: the role of platform/collective_helper.h NCCLCommContext —
the per-process registry mapping communicator namespaces to device resources.
On TPU the resource is a jax.sharding.Mesh; fleet's CommunicateTopology
declares logical axes (dp/pp/sharding/mp/sep...) and this registry realizes
them as one named device mesh whose fastest-varying axis rides the innermost
ICI dimension (SURVEY.md A.1 mapping note).
"""
import numpy as np
import jax
from jax.sharding import Mesh

_current_mesh = None


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh():
    return _current_mesh


def build_mesh(axis_names, axis_sizes, devices=None):
    """Create + register a Mesh. Axis order: outermost first (slowest ICI
    hops — dp/pp) to innermost last (mp on fastest ICI), matching the
    reference's rank layout mp→sharding→pp→dp innermost→outermost (A.1)."""
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(axis_sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(axis_sizes)
    return set_mesh(Mesh(arr, tuple(axis_names)))


def axis_size(axis):
    if _current_mesh is not None and axis in _current_mesh.shape:
        return _current_mesh.shape[axis]
    return 1


def mesh_axis_names():
    return tuple(_current_mesh.axis_names) if _current_mesh is not None \
        else ()
