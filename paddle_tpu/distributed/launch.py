"""fleetrun — multi-host launcher.

Reference parity: fleet/launch.py (launch:396) + launch_utils.py
(Cluster:59/Pod:173, env injection, watch loop). TPU topology note: the
reference spawns one process per GPU; on TPU the single-controller runtime
drives all local chips from ONE process per host, so the launcher starts one
trainer per host and wires the hosts together:
  * rendezvous over the native TCPStore (csrc/tcp_store.cc) instead of the
    reference's gloo HTTP/FS KV — node 0 serves the store;
  * each node registers its endpoint; a barrier releases once all arrive;
  * the trainer env gets PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/
    PADDLE_TRAINER_ENDPOINTS (reference names) plus the jax.distributed
    coordinator address for the PJRT DCN handshake;
  * a watch loop restarts-or-aborts on child death (elastic mode defers to
    ElasticManager).

Usage:
  python -m paddle_tpu.distributed.launch [--nnodes N] [--node_rank R]
      [--master HOST:PORT] [--elastic] train.py [args...]
"""
import argparse
import os
import signal
import subprocess
import sys
import time


def _parse():
    p = argparse.ArgumentParser('fleetrun')
    p.add_argument('--nnodes', type=int,
                   default=int(os.environ.get('PADDLE_NNODES', 1)))
    p.add_argument('--node_rank', type=int,
                   default=int(os.environ.get('PADDLE_NODE_RANK', 0)))
    p.add_argument('--master',
                   default=os.environ.get('PADDLE_MASTER',
                                          '127.0.0.1:6170'))
    p.add_argument('--elastic', action='store_true')
    p.add_argument('--max_restarts', type=int, default=3)
    p.add_argument('--log_dir', default=None)
    p.add_argument('training_script')
    p.add_argument('training_script_args', nargs=argparse.REMAINDER)
    return p.parse_args()


def _rendezvous(args):
    """Register this node, learn the full endpoint list."""
    from ..core.native import TCPStore
    host, port = args.master.rsplit(':', 1)
    port = int(port)
    is_master = args.node_rank == 0
    store = TCPStore(host=host, port=port, is_master=is_master, timeout=120)
    my_ep = f"{host if is_master else _my_ip()}:{port + 1 + args.node_rank}"
    store.set(f"ep/{args.node_rank}", my_ep)
    store.barrier('rendezvous', args.nnodes)
    eps = [store.get(f"ep/{i}").decode() for i in range(args.nnodes)]
    return store, eps


def _my_ip():
    import socket
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(('8.8.8.8', 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return '127.0.0.1'


def _trainer_env(args, endpoints):
    env = dict(os.environ)
    host, port = args.master.rsplit(':', 1)
    # the jax.distributed coordinator gets its own port — the master port
    # itself is the rendezvous TCP store
    coord = f"{host}:{int(port) + 977}"
    env.update({
        'PADDLE_TRAINER_ID': str(args.node_rank),
        'PADDLE_TRAINERS_NUM': str(args.nnodes),
        'PADDLE_CURRENT_ENDPOINT': endpoints[args.node_rank],
        'PADDLE_TRAINER_ENDPOINTS': ','.join(endpoints),
        # PJRT multi-host handshake (jax.distributed)
        'JAX_COORDINATOR_ADDRESS': coord,
        'JAX_NUM_PROCESSES': str(args.nnodes),
        'JAX_PROCESS_ID': str(args.node_rank),
    })
    if args.log_dir:
        # trainers write rank-aware JSON-lines (fleet.utils.log_util)
        # plus watchdog/OOM reports next to the launcher's trainer logs;
        # an explicit --log_dir overrides any inherited FLEET_LOG_DIR
        env['FLEET_LOG_DIR'] = args.log_dir
    return env


def start_local_trainer(args, endpoints):
    """Parity: launch_utils.start_local_trainers (one proc per host)."""
    env = _trainer_env(args, endpoints)
    cmd = [sys.executable, '-u', args.training_script] + \
        args.training_script_args
    stdout = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        stdout = open(os.path.join(
            args.log_dir, f"trainer.{args.node_rank}.log"), 'a')
    return subprocess.Popen(cmd, env=env, stdout=stdout,
                            stderr=subprocess.STDOUT if stdout else None)


def watch_loop(args, endpoints, store):
    """Parity: launch_utils.watch_local_trainers — restart (elastic) or
    abort the pod on child death."""
    restarts = 0
    proc = start_local_trainer(args, endpoints)

    def forward_signal(signum, frame):
        proc.send_signal(signum)
    signal.signal(signal.SIGTERM, forward_signal)

    from .fleet.utils import log_util
    while True:
        ret = proc.poll()
        if ret is None:
            if args.elastic:
                store.set(f"heartbeat/{args.node_rank}",
                          str(time.time()))
            time.sleep(3)
            continue
        if ret == 0:
            return 0
        if args.elastic and restarts < args.max_restarts:
            restarts += 1
            log_util.log_json('trainer_restart', level='warning',
                              logger_name='launch', exit_code=ret,
                              restart=restarts,
                              max_restarts=args.max_restarts)
            proc = start_local_trainer(args, endpoints)
            continue
        log_util.log_json('pod_abort', level='error',
                          logger_name='launch', exit_code=ret,
                          node_rank=args.node_rank)
        return ret


class _NullStore:
    def set(self, *a, **k):
        pass

    def close(self):
        pass


def launch():
    """Parity: fleet/launch.py launch:396."""
    args = _parse()
    from .fleet.utils import log_util
    log_util.set_role('launcher')
    if args.log_dir:
        os.environ['FLEET_LOG_DIR'] = args.log_dir
        log_util.configure(log_dir=args.log_dir, force=True)
    log_util.log_json('fleetrun_start', logger_name='launch',
                      nnodes=args.nnodes, node_rank=args.node_rank,
                      master=args.master, elastic=bool(args.elastic))
    if args.nnodes <= 1:
        if args.elastic:
            ret = watch_loop(args, ['127.0.0.1:6171'], _NullStore())
            sys.exit(ret)
        env = _trainer_env(args, ['127.0.0.1:6171'])
        cmd = [sys.executable, '-u', args.training_script] + \
            args.training_script_args
        ret = subprocess.call(cmd, env=env)
        sys.exit(ret)
    store, endpoints = _rendezvous(args)
    ret = watch_loop(args, endpoints, store)
    store.barrier('teardown', args.nnodes)
    store.close()
    sys.exit(ret)


if __name__ == '__main__':
    launch()
