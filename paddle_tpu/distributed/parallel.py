"""Data-parallel wrapper + process launch helpers.

Reference parity: python/paddle/fluid/dygraph/parallel.py DataParallel:382
(C++ Reducer N21 underneath) and distributed/parallel.py init_parallel_env /
spawn. TPU-native: gradient sync is an XLA AllReduce — in the jitted SPMD
train step it is inserted by the partitioner from sharding annotations; in
the eager API path DataParallel.apply_collective_grads issues the collective
explicitly (bucketed like Reducer::FusedAllReduceSchedule, reducer.cc:798).
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.base import Layer
from . import collective
from .env import parallel_env, get_rank, get_world_size


class DataParallel(Layer):
    """Parity: paddle.DataParallel (fluid/dygraph/parallel.py:382)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, fp16_allreduce=False):
        super().__init__()
        self._layers = layers
        self.group = group
        self.comm_buffer_size_mb = comm_buffer_size
        self.find_unused_parameters = find_unused_parameters
        # compress grads to bf16 on the wire (parity:
        # fp16_allreduce_optimizer.py; bf16 is the TPU-native half format)
        self.fp16_allreduce = fp16_allreduce

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Bucketed grad allreduce (parity: Reducer::FusedAllReduceSchedule,
        reducer.cc:798 + AssignGroupBySize:985). Buckets are concatenated
        flat buffers so each AllReduce moves one large contiguous block."""
        if get_world_size(self.group) <= 1 and \
                not collective.in_spmd_region():
            return
        params = [p for p in self._layers.parameters()
                  if not p.stop_gradient and p.grad is not None]
        if not params:
            return
        limit = self.comm_buffer_size_mb * 1024 * 1024
        bucket, size = [], 0
        buckets = []
        for p in params:
            nbytes = p.grad.size * p.grad.data.dtype.itemsize
            bucket.append(p)
            size += nbytes
            if size >= limit:
                buckets.append(bucket)
                bucket, size = [], 0
        if bucket:
            buckets.append(bucket)
        for bucket in buckets:
            flat = jnp.concatenate([p.grad.data.reshape(-1)
                                    for p in bucket])
            wire_dtype = flat.dtype
            if self.fp16_allreduce:
                flat = flat.astype(jnp.bfloat16)
            t = Tensor(flat)
            collective.all_reduce(t, group=self.group)
            scale = 1.0 / get_world_size(self.group)
            flat = t.data.astype(wire_dtype) * scale
            off = 0
            for p in bucket:
                n = p.grad.size
                p.grad.data = flat[off:off + n].reshape(
                    p.grad.data.shape).astype(p.grad.dtype)
                off += n

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self


def scale_loss(loss):
    return loss


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Parity: paddle.distributed.spawn (spawn.py:333). Single-controller
    TPU runtime drives all local chips from one process, so spawn degrades
    to a direct call with rank env prepared; multi-host launch is fleetrun's
    job (one process per host)."""
    import os
    if nprocs in (-1, 0, 1) or parallel_env().world_size <= 1:
        os.environ.setdefault('PADDLE_TRAINER_ID', '0')
        func(*args)
        return
    raise NotImplementedError(
        "multi-process spawn is replaced by the single-controller runtime; "
        "use paddle_tpu.distributed.launch (fleetrun) for multi-host")
