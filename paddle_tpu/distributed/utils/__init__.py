"""distributed.utils (launch helpers re-export)."""
