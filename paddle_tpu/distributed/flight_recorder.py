"""Collective flight recorder + hang watchdog.

Reference parity role: the collective-op debug journal the reference
keeps behind FLAGS (NCCLCommContext ring logging / gen_comm_id debug)
plus the elastic watch loop's death detection — fused into the
post-mortem tool the ISSUE-2 blind spot needs: when a rank hangs in (or
never reaches) a collective, produce a cross-rank report of "rank R
never entered <op> seq=N" instead of a silent wedge.

Two pieces:

  * `FlightRecorder` — a per-rank fixed-size ring journal. Every
    collective records (seq, op, group, shape, bytes, enqueue ts) on
    entry and stamps a completion ts on exit. `seq` is process-monotonic;
    host-backend collectives additionally journal their group-level
    sequence number (`gseq`) — the number that must advance in lockstep
    across ranks, i.e. the thing a hang report is phrased in.
  * `HangWatchdog` — a daemon thread that declares "no progress" when
    the oldest incomplete journal entry is older than `timeout`, or when
    the step heartbeat (stamped by the engines' train steps) goes stale.
    On trigger it captures all Python thread stacks, publishes its local
    dump under `fr/<job>/<rank>` on the TCPStore, gathers the peer
    ranks' dumps from the same namespace (every healthy-but-blocked rank
    has its own watchdog publishing), writes a combined per-rank report
    file, and optionally aborts the process (so fleetrun's watch loop
    can relaunch instead of burning a slot forever).

`analyze(dumps)` turns gathered per-rank journals into the cross-rank
verdict: last completed + first missing group-seq per rank, and which
rank(s) stalled the fleet.
"""
import contextlib
import json
import os
import sys
import threading
import time
import traceback

__all__ = ['FlightRecorder', 'recorder', 'record_span', 'heartbeat',
           'HangWatchdog', 'analyze', 'render_dump', 'start_watchdog',
           'stop_watchdog']

_DISABLED = os.environ.get('PADDLE_FLIGHT_RECORDER', '1') in ('0', 'off')


def _env_int(name, default):
    try:
        return int(os.environ.get(name, '') or default)
    except ValueError:
        return default


class FlightRecorder:
    """Fixed-size ring journal of collective operations (thread-safe)."""

    def __init__(self, capacity=512, rank=None):
        self.capacity = int(capacity)
        if self.capacity <= 0:
            raise ValueError("flight recorder capacity must be >= 1")
        self.rank = _env_int('PADDLE_TRAINER_ID', 0) \
            if rank is None else int(rank)
        self._lock = threading.Lock()
        self._entries = {}            # seq -> entry (only in-ring seqs)
        self._order = []              # ring of live seqs, oldest first
        self._seq = 0
        self._dropped = 0
        self._completed = 0
        self._last_completed = 0
        self._last_beat = None        # step heartbeat (engines stamp it)

    # -- journal -------------------------------------------------------------
    def record_enqueue(self, op, group=0, gseq=None, shape=None,
                       nbytes=0, mode='eager'):
        """Journal a collective entering its transport; returns the
        process-monotonic seq used to stamp completion."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            entry = {
                'seq': seq, 'op': str(op), 'group': group,
                'gseq': gseq, 'shape': list(shape) if shape else None,
                'bytes': int(nbytes), 'mode': mode,
                't_enqueue': time.time(), 't_complete': None, 'ok': None,
            }
            self._entries[seq] = entry
            self._order.append(seq)
            if len(self._order) > self.capacity:
                # evict the oldest COMPLETED entry: a pending one is the
                # hang evidence this ring exists to keep — evicting it
                # would disarm the watchdog's stalled-collective check
                # mid-hang and erase the hung op from the dump. All
                # pending (pathological) falls back to oldest-any so
                # memory stays bounded.
                for i, s in enumerate(self._order):
                    if self._entries[s]['t_complete'] is not None:
                        old = self._order.pop(i)
                        break
                else:
                    old = self._order.pop(0)
                self._entries.pop(old, None)
                self._dropped += 1
            return seq

    def record_complete(self, seq, ok=True):
        with self._lock:
            self._completed += 1
            self._last_completed = max(self._last_completed, seq)
            e = self._entries.get(seq)
            if e is not None:        # may have wrapped out of the ring
                e['t_complete'] = time.time()
                e['ok'] = bool(ok)

    @contextlib.contextmanager
    def span(self, op, group=0, gseq=None, shape=None, nbytes=0,
             mode='eager'):
        seq = self.record_enqueue(op, group=group, gseq=gseq, shape=shape,
                                  nbytes=nbytes, mode=mode)
        ok = True
        try:
            yield seq
        except BaseException:
            ok = False
            raise
        finally:
            self.record_complete(seq, ok=ok)

    def heartbeat(self):
        """Stamp step-level liveness (engines call this per train step)."""
        with self._lock:
            self._last_beat = time.time()

    def clear_heartbeat(self):
        """Disarm step-liveness detection (engine teardown: a stale beat
        after a deliberate stop is not a hang)."""
        with self._lock:
            self._last_beat = None

    # -- queries -------------------------------------------------------------
    def seq(self):
        with self._lock:
            return self._seq

    def last_completed_seq(self):
        with self._lock:
            return self._last_completed

    def first_incomplete(self):
        """Oldest journal entry still lacking a completion stamp."""
        with self._lock:
            for s in self._order:
                e = self._entries[s]
                if e['t_complete'] is None:
                    return dict(e)
        return None

    def last_beat(self):
        with self._lock:
            return self._last_beat

    def entries(self):
        with self._lock:
            return [dict(self._entries[s]) for s in self._order]

    def dropped(self):
        with self._lock:
            return self._dropped

    def dump(self):
        with self._lock:
            entries = [dict(self._entries[s]) for s in self._order]
            last_gseq = None
            first_missing_gseq = None
            first_missing_op = None
            for e in entries:
                if e['gseq'] is None:
                    continue
                if e['t_complete'] is not None:
                    if last_gseq is None or e['gseq'] > last_gseq:
                        last_gseq = e['gseq']
                elif first_missing_gseq is None:
                    first_missing_gseq = e['gseq']
                    first_missing_op = e['op']
            return {
                'kind': 'flight_recorder',
                'rank': self.rank,
                'pid': os.getpid(),
                'time': time.time(),
                'capacity': self.capacity,
                'dropped': self._dropped,
                'seq': self._seq,
                'completed': self._completed,
                'last_completed_seq': self._last_completed,
                'last_completed_gseq': last_gseq,
                'first_incomplete_gseq': first_missing_gseq,
                'first_incomplete_op': first_missing_op,
                'last_heartbeat': self._last_beat,
                'entries': entries,
            }

    def reset(self):
        with self._lock:
            self._entries.clear()
            self._order = []
            self._seq = 0
            self._dropped = 0
            self._completed = 0
            self._last_completed = 0
            self._last_beat = None


_recorder = FlightRecorder(
    capacity=max(1, _env_int('PADDLE_FLIGHT_RECORDER_CAPACITY', 512)))


def recorder():
    return _recorder


def heartbeat():
    if not _DISABLED:
        _recorder.heartbeat()


def engine_teardown():
    """Called by the engines' shutdown(): stop the env-gated watchdog
    and disarm the step heartbeat so a deliberate stop (teardown, eval,
    checkpointing after the last step) can't fire a false hang report."""
    stop_watchdog()
    _recorder.clear_heartbeat()


@contextlib.contextmanager
def record_span(op, group=0, gseq=None, shape=None, nbytes=0,
                mode='eager'):
    """Journal one collective through the process-global recorder (the
    hot-path entry point; no-op ring write when disabled via env)."""
    if _DISABLED:
        yield None
        return
    with _recorder.span(op, group=group, gseq=gseq, shape=shape,
                        nbytes=nbytes, mode=mode) as seq:
        yield seq


def _thread_stacks():
    """JSON-able Python stacks of every live thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for tid, frame in sys._current_frames().items():
        stacks[f'{names.get(tid, "?")}:{tid}'] = \
            traceback.format_stack(frame)
    return stacks


# ---------------------------------------------------------------------------
# cross-rank analysis
# ---------------------------------------------------------------------------
def analyze(dumps):
    """`dumps`: {rank: dump-dict-or-None}. Returns the cross-rank hang
    verdict: per-rank last-completed / first-missing group seq, the
    fleet-wide frontier, and human sentences naming the stalled ranks
    ("rank 1 never entered all_reduce gseq=4")."""
    ranks = {}
    frontier = None
    for r, d in sorted(dumps.items()):
        if not d:
            ranks[int(r)] = None
            continue
        row = {
            'last_completed_seq': d.get('last_completed_seq'),
            'last_completed_gseq': d.get('last_completed_gseq'),
            'first_incomplete_gseq': d.get('first_incomplete_gseq'),
            'first_incomplete_op': d.get('first_incomplete_op'),
            'dropped': d.get('dropped'),
            'last_heartbeat': d.get('last_heartbeat'),
        }
        ranks[int(r)] = row
        # the frontier is the furthest collective any rank ATTEMPTED —
        # a pending entry counts (the blocked rank got there; the rank
        # that never entered it is the suspect)
        for g in (row['last_completed_gseq'],
                  row['first_incomplete_gseq']):
            if g is not None:
                frontier = g if frontier is None else max(frontier, g)

    # name of the op at a given gseq, learned from any rank that saw it
    op_at = {}
    for d in dumps.values():
        for e in (d or {}).get('entries', ()):
            if e.get('gseq') is not None:
                op_at.setdefault(e['gseq'], e['op'])

    stalled, summary = [], []
    for r, row in sorted(ranks.items()):
        if row is None:
            stalled.append(r)
            summary.append(f"rank {r}: no dump received — process dead "
                           "or unreachable")
            continue
        last = row['last_completed_gseq']
        pend = row['first_incomplete_gseq']
        if pend is not None:
            summary.append(
                f"rank {r}: entered {row['first_incomplete_op']} "
                f"gseq={pend} but never completed it "
                f"(last completed gseq={last})")
        elif frontier is not None and (last is None or last < frontier):
            missing = 0 if last is None else last + 1
            op = op_at.get(missing, '<unknown op>')
            stalled.append(r)
            summary.append(
                f"rank {r} never entered {op} gseq={missing} "
                f"(last completed gseq={last}) — suspect stalled rank")
        else:
            summary.append(f"rank {r}: at the fleet frontier "
                           f"(gseq={last})")
    return {'frontier_gseq': frontier, 'ranks': ranks,
            'stalled_ranks': stalled, 'summary': summary}


def render_dump(doc):
    """Human rendering of a combined watchdog report (or a bare per-rank
    dump) — shared with tools/health_dump.py."""
    out = ['== flight recorder ' + '=' * 41]
    if doc.get('kind') == 'flight_recorder':       # single-rank dump
        doc = {'ranks': {doc['rank']: doc}, 'analysis': None,
               'reason': None}
    if doc.get('reason'):
        out.append(f"watchdog trigger: {doc['reason']}")
    ana = doc.get('analysis')
    if ana:
        out.append(f"fleet frontier gseq: {ana.get('frontier_gseq')}   "
                   f"stalled ranks: {ana.get('stalled_ranks')}")
        for line in ana.get('summary', ()):
            out.append('  ' + line)
    for r, d in sorted(doc.get('ranks', {}).items(),
                       key=lambda kv: int(kv[0])):
        out.append(f"-- rank {r} " + '-' * 49)
        if not d:
            out.append('  (no dump)')
            continue
        out.append(
            f"  seq={d.get('seq')} completed={d.get('completed')} "
            f"last_gseq={d.get('last_completed_gseq')} "
            f"pending_gseq={d.get('first_incomplete_gseq')} "
            f"dropped={d.get('dropped')}")
        for e in d.get('entries', [])[-8:]:
            state = 'ok' if e.get('t_complete') else 'PENDING'
            gseq = e.get('gseq')
            out.append(
                f"  seq={e['seq']:<5} {e['op']:<24} "
                f"group={e.get('group')} "
                + (f"gseq={gseq} " if gseq is not None else '')
                + f"bytes={e.get('bytes', 0)} [{state}]")
    return '\n'.join(out)


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------
class HangWatchdog:
    """No-progress detector over the flight recorder.

    Triggers when (a) the oldest incomplete journal entry is older than
    `timeout` seconds, or (b) a step heartbeat was ever recorded and has
    been stale for `timeout`. On trigger: dump the journal + all Python
    thread stacks; with a TCPStore, rendezvous with the peer ranks'
    watchdogs and write ONE combined cross-rank report per rank under
    `dump_dir`. Daemon-threaded; `stop()` is idempotent and joins.
    """

    def __init__(self, timeout=60.0, interval=None, store=None, rank=None,
                 world_size=None, job_id=None, dump_dir=None,
                 recorder=None, on_dump=None, gather_timeout=None,
                 abort=False):
        self.timeout = float(timeout)
        self.interval = float(interval) if interval else \
            max(0.25, min(self.timeout / 4.0, 5.0))
        self.store = store
        self.rank = _env_int('PADDLE_TRAINER_ID', 0) \
            if rank is None else int(rank)
        self.world_size = _env_int('PADDLE_TRAINERS_NUM', 1) \
            if world_size is None else int(world_size)
        self.job_id = job_id or os.environ.get('PADDLE_ELASTIC_JOB_ID',
                                               'default_job')
        if dump_dir is None:
            from ..core.memory import default_report_dir
            dump_dir = default_report_dir()
        self.dump_dir = dump_dir
        self.recorder = recorder if recorder is not None else _recorder
        self.on_dump = on_dump
        self.gather_timeout = float(gather_timeout) if gather_timeout \
            else max(2.0, self.timeout / 2.0)
        self.abort = abort
        self.fired = threading.Event()
        self.fire_count = 0
        self.report_path = None
        self._stop = threading.Event()
        self._thread = None
        self._own_store = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name='ptpu-hang-watchdog', daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(2.0, self.interval * 3))
            self._thread = None
        if self._own_store is not None:
            try:
                self._own_store.close()
            except Exception:
                pass
            self._own_store = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()
        return False

    # -- detection -----------------------------------------------------------
    def _stall_reason(self, now):
        pending = self.recorder.first_incomplete()
        if pending is not None and \
                now - pending['t_enqueue'] > self.timeout:
            age = now - pending['t_enqueue']
            where = f"gseq={pending['gseq']}" if pending['gseq'] is not \
                None else f"seq={pending['seq']}"
            return (f"collective {pending['op']} {where} pending for "
                    f"{age:.1f}s (> {self.timeout:.1f}s deadline)")
        beat = self.recorder.last_beat()
        if beat is not None and now - beat > self.timeout:
            return (f"step heartbeat stale for {now - beat:.1f}s "
                    f"(> {self.timeout:.1f}s deadline)")
        return None

    def _loop(self):
        while not self._stop.wait(self.interval):
            reason = self._stall_reason(time.time())
            if reason is None:
                continue
            try:
                self._fire(reason)
            finally:
                self.fire_count += 1
                self.fired.set()
            if self.abort and not self._stop.is_set():
                os._exit(3)
            # episode latch: one report per stall. Wait for progress to
            # resume, then RE-ARM — a spurious fire (e.g. a timeout set
            # below a cold compile) must not disable detection of a real
            # hang later in the run.
            while not self._stop.wait(self.interval):
                if self._stall_reason(time.time()) is None:
                    break

    # -- dump + rendezvous ---------------------------------------------------
    def _key(self, rank):
        return f'fr/{self.job_id}/{rank}'

    def _dump_store(self):
        """A DEDICATED TCPStore connection for publishing dumps. The
        training client serializes every op behind one mutex held across
        blocking waits (tcp_store.cc Get('W')/Barrier hold mu_ until the
        server answers) — exactly the mutex the hung collective owns, so
        sharing that client would deadlock the watchdog at the moment it
        exists to act."""
        s = self.store
        if s is None:
            return None
        if self._own_store is not None:
            return self._own_store
        host, port = getattr(s, 'host', None), getattr(s, 'port', None)
        if host and port:
            try:
                from ..core.native import TCPStore
                self._own_store = TCPStore(host=host, port=port,
                                           is_master=False, timeout=10)
                return self._own_store
            except Exception:
                # reconnect failed: dump locally rather than risk the
                # shared client — blocking on its held mutex wouldn't
                # even raise, it would wedge this thread for good
                return None
        return s       # non-native store (tests): no C mutex to share

    @staticmethod
    def _publish_payload(local, limit=900_000):
        """The cross-rank copy of a dump, bounded under the TCPStore
        get cap (the C client truncates reads at 1 MiB — a peer
        receiving a truncated JSON would misreport this HEALTHY rank as
        dead). Stacks (source lines, unbounded) stay local-only; the
        journal tail shrinks until the payload fits."""
        trimmed = {k: v for k, v in local.items() if k != 'stacks'}
        for tail in (128, 32, 8):
            data = json.dumps(trimmed).encode()
            if len(data) <= limit:
                return data
            trimmed['entries'] = trimmed['entries'][-tail:]
            trimmed['entries_trimmed_to'] = tail
        return json.dumps(trimmed).encode()

    def _fire(self, reason):
        local = self.recorder.dump()
        local['stacks'] = _thread_stacks()
        local['watchdog_reason'] = reason
        dumps = {self.rank: local}
        store = self._dump_store()
        if store is not None and self.world_size > 1:
            try:
                store.set(self._key(self.rank),
                          self._publish_payload(local))
            except Exception:
                pass
            deadline = time.time() + self.gather_timeout
            missing = [r for r in range(self.world_size)
                       if r != self.rank]
            while missing and time.time() < deadline \
                    and not self._stop.is_set():
                for r in list(missing):
                    try:
                        v = store.get(self._key(r), wait=False)
                    except Exception:
                        v = None
                    if v:
                        try:
                            dumps[r] = json.loads(v.decode())
                        except ValueError:
                            dumps[r] = None
                        missing.remove(r)
                if missing:
                    time.sleep(0.2)
            for r in missing:
                dumps[r] = None
        report = {
            'kind': 'hang_report',
            'time': time.time(),
            'detector_rank': self.rank,
            'world_size': self.world_size,
            'reason': reason,
            'ranks': {str(r): d for r, d in dumps.items()},
            'analysis': analyze(dumps),
        }
        self.report_path = self._write(report)
        try:
            from .fleet.utils import log_util
            log_util.log_json(
                'hang_detected', level='error', reason=reason,
                report_path=self.report_path,
                stalled_ranks=report['analysis']['stalled_ranks'])
        except Exception:
            pass
        if self.on_dump is not None:
            try:
                self.on_dump(report)
            except Exception:
                pass
        return report

    def _write(self, report):
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir,
                f'flight_recorder.rank{self.rank}.{os.getpid()}.json')
            with open(path, 'w') as f:
                json.dump(report, f)
            return path
        except OSError:
            return None


# ---------------------------------------------------------------------------
# process-level convenience: env-gated singleton watchdog
# ---------------------------------------------------------------------------
_watchdog = None


def start_watchdog(timeout=None, store=None, **kwargs):
    """Start (once) the process watchdog over the global recorder. With
    no explicit `timeout` it is gated on PADDLE_HANG_TIMEOUT — the
    engines call this every step, so exporting that env is all a
    production job needs. The TCPStore defaults to the host-collective
    group's when one is initialized (cross-rank dumps for free)."""
    global _watchdog
    if _watchdog is not None:
        return _watchdog
    if timeout is None:
        try:
            timeout = float(os.environ.get('PADDLE_HANG_TIMEOUT',
                                           '0') or 0)
        except ValueError:
            timeout = 0.0
        if timeout <= 0:
            return None
    if store is None:
        try:
            from . import host_collectives as HC
            g = HC.host_group()
            store = g.store if g is not None else None
        except Exception:
            store = None
    _watchdog = HangWatchdog(timeout=timeout, store=store,
                             **kwargs).start()
    return _watchdog


def stop_watchdog():
    global _watchdog
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None
