"""paddle_tpu.distributed — collective API + fleet.

Reference parity: python/paddle/distributed/__init__.py surface
(SURVEY.md §1-L8).
"""
from .env import (ParallelEnv, get_rank, get_world_size, is_initialized,
                  parallel_env)
from .collective import (ReduceOp, Group, new_group, get_group,
                         init_parallel_env, destroy_process_group, wait,
                         barrier, all_reduce, reduce, broadcast, all_gather,
                         reduce_scatter, scatter, alltoall, alltoall_single,
                         send, recv, isend, irecv, ppermute, shift, split,
                         spmd_region, in_spmd_region,
                         _c_identity, _mp_allreduce, _c_concat, _c_split,
                         _c_softmax_with_cross_entropy, _c_embedding)
from .parallel import DataParallel, spawn
from . import topology_runtime
from . import fleet
from . import utils


def get_backend():
    return 'xla'

from .entry_attr import (EntryAttr, ProbabilityEntry,  # noqa
                         CountFilterEntry)
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa
