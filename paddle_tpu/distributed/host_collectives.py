"""Host-mediated eager collectives over the native TCPStore.

Reference parity: the Gloo CPU-collective role (platform/gloo_context.cc,
framework/fleet/gloo_wrapper.h N9) and the eager dygraph collectives that do
real cross-process work (imperative/all_reduce.cc, nccl_context.cc:199).
On TPU the *performance* path for collectives is XLA over ICI inside SPMD
programs; this module serves the eager API outside SPMD regions — parameter
broadcast at init, found_inf/metric sync, DataParallel grad sync in the
non-jitted path — where the reference uses NCCL/Gloo and a silent identity
would be wrong (r1 VERDICT weak #3).

Transport: the fleetrun TCPStore (csrc/tcp_store.cc). Every rank writes its
chunked payload under a per-rank key tagged with a monotonically increasing
sequence number, reads all ranks' payloads, then passes a store barrier
before the next collective may overwrite the slots. Store memory stays
bounded: data keys are reused (seq-tagged), only the tiny per-seq barrier
counters accumulate.
"""
import os
import struct
import time

import numpy as np

_CHUNK = 512 * 1024
_group = None


class HostCollectiveGroup:
    def __init__(self, store, rank, world_size, gid=0):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.gid = gid
        self._seq = 0

    def _journal(self, op, arr=None):
        """Flight-record this collective under its GROUP sequence number
        — the number that must advance in lockstep on every rank, so a
        hang report can say 'rank R never entered <op> gseq=N'."""
        from . import flight_recorder as _fr
        shape = getattr(arr, 'shape', None)
        nbytes = int(getattr(arr, 'nbytes', 0) or 0)
        return _fr.record_span(op, group=self.gid, gseq=self._seq,
                               shape=shape, nbytes=nbytes, mode='host')

    # -- plumbing ------------------------------------------------------------
    def _put(self, payload):
        nchunks = max(1, (len(payload) + _CHUNK - 1) // _CHUNK)
        for c in range(nchunks):
            chunk = payload[c * _CHUNK:(c + 1) * _CHUNK]
            self.store.set(f'hc/{self.gid}/{self.rank}/{c}',
                           struct.pack('<q', self._seq) + chunk)
        return nchunks

    def _get(self, rank, nbytes):
        nchunks = max(1, (nbytes + _CHUNK - 1) // _CHUNK)
        out = []
        for c in range(nchunks):
            key = f'hc/{self.gid}/{rank}/{c}'
            while True:
                v = self.store.get(key, wait=True)
                seq, = struct.unpack('<q', v[:8])
                if seq == self._seq:
                    out.append(v[8:])
                    break
                if seq > self._seq:
                    raise RuntimeError(
                        f"host collective out of sync: rank {rank} at seq "
                        f"{seq}, local {self._seq} — ranks must issue "
                        "collectives in the same order")
                time.sleep(0.001)
        return b''.join(out)

    def _round(self, arr):
        """One exchange: returns list of every rank's array."""
        a = np.ascontiguousarray(arr)
        self._put(a.tobytes())
        vals = []
        for r in range(self.world_size):
            if r == self.rank:
                vals.append(a)
            else:
                vals.append(np.frombuffer(
                    self._get(r, a.nbytes), dtype=a.dtype).reshape(a.shape))
        self.store.barrier(f'hc/b/{self.gid}/{self._seq}', self.world_size)
        self._seq += 1
        return vals

    # -- collectives ---------------------------------------------------------
    def all_gather(self, arr):
        a = np.asarray(arr)
        with self._journal('all_gather', a):
            return self._round(a)

    def all_reduce(self, arr, op='sum'):
        a = np.asarray(arr)
        with self._journal('all_reduce', a):
            vals = self._round(a)
        if op == 'sum':
            return sum(vals[1:], vals[0].copy())
        if op == 'avg':
            return sum(vals[1:], vals[0].astype(np.float64)) \
                / self.world_size
        if op == 'max':
            return np.maximum.reduce(vals)
        if op == 'min':
            return np.minimum.reduce(vals)
        if op == 'prod':
            out = vals[0].copy()
            for v in vals[1:]:
                out = out * v
            return out
        raise ValueError(f"bad reduce op {op}")

    def broadcast(self, arr, src=0):
        """src uploads once; everyone reads src's slot (1/W the traffic
        of an all-gather round)."""
        a = np.ascontiguousarray(np.asarray(arr))
        with self._journal('broadcast', a):
            if self.rank == src:
                self._put(a.tobytes())
                out = a
            else:
                out = np.frombuffer(self._get(src, a.nbytes),
                                    dtype=a.dtype).reshape(a.shape)
            self.store.barrier(f'hc/b/{self.gid}/{self._seq}',
                               self.world_size)
        self._seq += 1
        return out

    def barrier(self):
        with self._journal('barrier'):
            self.store.barrier(f'hc/bar/{self.gid}/{self._seq}',
                               self.world_size)
        self._seq += 1


def init_host_collectives(rank=None, world_size=None, master=None,
                          timeout=60):
    """Connect (rank 0: host) the collective TCPStore. Uses
    PADDLE_MASTER's port + 7 so it never clashes with the fleetrun
    rendezvous server that the launcher owns."""
    global _group
    if _group is not None:
        return _group
    from ..core.native import TCPStore
    if rank is None:
        rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    if world_size is None:
        world_size = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    if world_size <= 1:
        return None
    if master is None:
        master = os.environ.get('PADDLE_MASTER')
        if not master:
            eps = os.environ.get('PADDLE_TRAINER_ENDPOINTS', '')
            master = eps.split(',')[0] if eps else None
    if not master:
        raise RuntimeError(
            "host collectives need PADDLE_MASTER or "
            "PADDLE_TRAINER_ENDPOINTS to locate the TCP store")
    host, port = master.rsplit(':', 1)
    port = int(port) + 7
    store = TCPStore(host=host, port=port, is_master=(rank == 0),
                     timeout=timeout)
    _group = HostCollectiveGroup(store, rank, world_size)
    return _group


def host_group():
    return _group


def shutdown():
    global _group
    if _group is not None:
        _group.store.close()
        _group = None
