"""Collective communication API.

Reference parity: python/paddle/distributed/collective.py (new_group:209,
all_reduce:415, broadcast:348, all_gather:589, scatter:667, alltoall:1456,
send:1528, recv:1578, barrier:167, and the model-parallel helpers
_c_identity:748.._parallel_embedding:1178, split:1283) over the C++
operators/collective/ op zoo (N24) and NCCLCommContext ring registry (N7).

TPU-native design — the ring_id→ncclComm map becomes a Group→mesh-axis map:
  * Inside an SPMD region (a shard_map/pjit trace entered via
    paddle_tpu.distributed.spmd or the fleet engines), each collective lowers
    to the XLA collective on the group's mesh axes: psum → AllReduce over ICI,
    all_gather → AllGather, reduce_scatter → ReduceScatter, alltoall →
    AllToAll, send/recv → CollectivePermute. XLA assigns channel ids — the
    TPU analogue of ring ids.
  * Outside (pure eager, single process): world_size==1 ⇒ collectives are
    identities, matching the reference's degenerate behavior.
"""
import contextlib
import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.autograd import run_op
from .env import parallel_env, get_rank, get_world_size


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """Parity: collective.py Group — here it names mesh axes instead of an
    NCCL ring (A.3c's magic ring-id ints become axis names)."""

    _next_id = 0

    def __init__(self, rank, nranks, id=None, ranks=None, axis_name=None):
        self.rank = rank
        self.nranks = nranks
        if id is None:
            id = Group._next_id
        Group._next_id = max(Group._next_id + 1, id + 1)
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name  # mesh axis (str or tuple) in SPMD regions

    def is_member(self):
        return self.rank >= 0

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return (f"Group(id={self.id}, nranks={self.nranks}, "
                f"axis={self.axis_name})")


_default_group = None
_group_map = {}

# ---- SPMD region bookkeeping ------------------------------------------------
_spmd_axes = []       # stack of tuples of active mesh axis names
_sp_data_sharded = []  # stack of bools: is the BATCH sharded over 'sp'?
_mp_seq_parallel = []  # stack of bools: elementwise-segment activations
                       # sequence-sharded over the mp group (Megatron-SP)


@contextlib.contextmanager
def spmd_region(axis_names, sp_data_sharded=False, mp_seq_parallel=False):
    """Mark that we are tracing inside shard_map over `axis_names`. The fleet
    engines enter this around their per-device step functions.
    `sp_data_sharded` declares that batch tensors are sequence-sharded over
    the 'sp' axis — models key sequence-parallel behavior off THIS, not off
    mere axis presence (an sp axis may exist for other tensors).
    `mp_seq_parallel` declares Megatron-style sequence-parallel activation
    sharding: the LayerNorm/dropout/residual segments BETWEEN mp regions
    run on token slices scattered over the mp group (row-parallel outputs
    psum_scatter along the sequence instead of allreduce, column-parallel
    inputs all_gather back — docs/performance.md#sequence-parallel-
    activations)."""
    _spmd_axes.append(tuple(axis_names))
    _sp_data_sharded.append(bool(sp_data_sharded))
    _mp_seq_parallel.append(bool(mp_seq_parallel))
    try:
        yield
    finally:
        _spmd_axes.pop()
        _sp_data_sharded.pop()
        _mp_seq_parallel.pop()


def sp_data_sharded():
    return bool(_sp_data_sharded and _sp_data_sharded[-1])


def mp_seq_sharded():
    """True when the engine declared sequence-parallel activation
    sharding over the mp group for this traced region."""
    return bool(_mp_seq_parallel and _mp_seq_parallel[-1])


def resolve_sequence_parallel(flag=None):
    """Sequence-parallel activation sharding knob, resolved engine kwarg
    -> PTPU_SEQUENCE_PARALLEL env -> fleet strategy
    tensor_parallel_configs['sequence_parallel'] -> False."""
    import os
    if flag is None:
        v = os.environ.get('PTPU_SEQUENCE_PARALLEL')
        if v is not None and v != '':
            flag = v.lower() in ('1', 'true', 'yes')
    if flag is None:
        try:
            from .fleet import fleet as _fleet_mod
            strategy = _fleet_mod._user_defined_strategy
            if strategy is not None:
                flag = (strategy.tensor_parallel_configs or {}).get(
                    'sequence_parallel')
        except Exception:
            flag = None
    return bool(flag)


def in_spmd_region():
    return bool(_spmd_axes)


def current_spmd_axes():
    return _spmd_axes[-1] if _spmd_axes else ()


def _group_axes(group):
    """Resolve the mesh axes a collective should run over."""
    if group is not None and group.axis_name is not None:
        ax = group.axis_name
        return ax if isinstance(ax, tuple) else (ax,)
    return current_spmd_axes()


_OP_NAMES = {ReduceOp.SUM: 'sum', ReduceOp.MAX: 'max', ReduceOp.MIN: 'min',
             ReduceOp.PROD: 'prod', ReduceOp.AVG: 'avg'}


# ---- observability ----------------------------------------------------------
def _tensor_bytes(*objs):
    """Payload bytes of the Tensor/array args (tracer-safe: shapes and
    dtypes are known on abstract values too)."""
    total = 0
    for o in objs:
        if isinstance(o, (list, tuple)):
            total += _tensor_bytes(*o)
            continue
        arr = o.data if isinstance(o, Tensor) else o
        shape = getattr(arr, 'shape', None)
        dtype = getattr(arr, 'dtype', None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape or (1,))) * jnp.dtype(dtype).itemsize
        except Exception:
            pass
    return total


def _instrumented(fn):
    """Per-collective telemetry: call count + payload bytes into
    core.monitor counters, a wall-time histogram, and a profiler span.
    Inside an SPMD trace the span measures TRACE time and the counters
    count per-trace (the executable replays them on device); eager
    host-backend collectives measure real wire time."""
    from ..core import monitor as _m
    op_name = fn.__name__
    span_name = f'collective::{op_name}'
    cache = {'epoch': None}

    def _handles():
        """Per-series metric children, re-resolved only when the
        registry was reset — keeps the hot path at one int compare
        instead of three lock-protected registry lookups per call."""
        reg = _m.metrics()
        if cache['epoch'] != reg.epoch:
            cache['calls'] = reg.counter(
                'ptpu_collective_calls_total',
                help='collective API invocations',
                labelnames=('op',)).labels(op=op_name)
            cache['bytes'] = reg.counter(
                'ptpu_collective_bytes_total',
                help='payload bytes through collective APIs',
                labelnames=('op',)).labels(op=op_name)
            cache['seconds'] = reg.histogram(
                'ptpu_collective_seconds',
                help='eager collective wall time',
                labelnames=('op',)).labels(op=op_name)
            cache['epoch'] = reg.epoch
        return cache

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        h = _handles()
        nbytes = _tensor_bytes(*args)
        h['calls'].inc(1)
        h['bytes'].inc(nbytes)
        from .. import profiler as _prof
        from . import flight_recorder as _fr
        traced = in_spmd_region()
        t0 = None if traced else time.perf_counter()
        grp = kwargs.get('group') or next(
            (a for a in args if isinstance(a, Group)), None)
        with _fr.record_span(op_name, nbytes=nbytes,
                             group=getattr(grp, 'id', 0),
                             mode='trace' if traced else 'eager'):
            with _prof.RecordEvent(span_name, event_type='collective',
                                   bytes=nbytes):
                out = fn(*args, **kwargs)
        if t0 is not None:
            h['seconds'].observe(time.perf_counter() - t0)
        return out
    return wrapper


def _host_backend(group):
    """Eager (outside-SPMD) multi-PROCESS backend, or None when this job
    is a single process. Keyed on the process count (PADDLE_TRAINERS_NUM),
    NOT device-derived world size: one process driving N chips does eager
    collectives as identities (cross-device work is the SPMD engines').
    A multi-process eager collective without a backend RAISES — the
    reference does real NCCL/Gloo work here (imperative/all_reduce.cc);
    a silent identity would train wrong."""
    import os
    nproc = int(os.environ.get('PADDLE_TRAINERS_NUM', '1') or '1')
    if nproc <= 1:
        return None
    if group is not None and group.axis_name is not None:
        return None   # mesh-axis group: collective belongs to SPMD regions
    if group is not None and group.nranks not in (0, nproc):
        raise NotImplementedError(
            "eager collectives over a sub-group are not supported outside "
            "SPMD regions; pass axis-named groups inside an SPMD region "
            "or use the world group")
    from . import host_collectives as HC
    g = HC.host_group() or HC.init_host_collectives()
    if g is None:
        raise RuntimeError(
            f"eager collective across {nproc} processes outside an SPMD "
            "region needs the TCPStore host backend (run under fleetrun / "
            "set PADDLE_MASTER) — refusing to silently no-op")
    return g


# ---- init / groups ----------------------------------------------------------
def init_parallel_env():
    """Parity: paddle.distributed.init_parallel_env (parallel.py:58) — the
    NCCL-id broadcast + comm init is replaced by the PJRT client handshake
    (jax.distributed over the fleetrun-provided coordinator for multi-host
    DCN)."""
    global _default_group
    import os
    env = parallel_env()
    if _default_group is None:
        n_proc = int(os.environ.get('JAX_NUM_PROCESSES', '1'))
        coord = os.environ.get('JAX_COORDINATOR_ADDRESS')
        # NB: do not probe jax.process_count() here — it initializes the
        # backend, after which distributed.initialize refuses to run.
        from jax._src import distributed as _jd
        already = getattr(_jd.global_state, 'client', None) is not None
        if n_proc > 1 and coord and not already:
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=n_proc,
                process_id=int(os.environ.get('JAX_PROCESS_ID', '0')))
        _default_group = Group(env.rank, env.world_size, id=0)
        _group_map[0] = _default_group
    return _default_group


def _get_default_group():
    if _default_group is None:
        return init_parallel_env()
    return _default_group


def get_group(id=0):
    return _group_map.get(id)


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    """Parity: collective.py new_group:209 — allocates a fresh communicator
    namespace. On TPU this is metadata only; XLA materializes the comm."""
    env = parallel_env()
    if ranks is None:
        ranks = list(range(env.world_size))
    rank = ranks.index(env.rank) if env.rank in ranks else -1
    g = Group(rank, len(ranks), ranks=list(ranks), axis_name=axis_name)
    _group_map[g.id] = g
    return g


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _group_map.clear()
        _default_group = None
    else:
        _group_map.pop(group.id, None)


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor.data,
                                              'block_until_ready'):
        tensor.data.block_until_ready()


@_instrumented
def barrier(group=None):
    """Parity: collective.py barrier:167."""
    if in_spmd_region():
        return
    hb = _host_backend(group)
    if hb is not None:
        hb.barrier()
        return
    # eager single-process: sync device
    for d in jax.live_arrays():
        d.block_until_ready()
        break


# ---- core collectives -------------------------------------------------------
def _psum_like(arr, op, axes):
    if op == ReduceOp.SUM:
        return lax.psum(arr, axes)
    if op == ReduceOp.MAX:
        return lax.pmax(arr, axes)
    if op == ReduceOp.MIN:
        return lax.pmin(arr, axes)
    if op == ReduceOp.AVG:
        return lax.pmean(arr, axes)
    if op == ReduceOp.PROD:
        return lax.pprod(arr, axes) if hasattr(lax, 'pprod') else \
            jnp.exp(lax.psum(jnp.log(arr), axes))
    raise ValueError(f"bad reduce op {op}")


@_instrumented
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    """Parity: c_allreduce_{sum,max,min,prod} (operators/collective/
    c_allreduce_op.h:268-301) → XLA AllReduce."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        out = run_op('c_allreduce', lambda a: _psum_like(a, op, axes),
                     [tensor])
        tensor._data = out._data
        tensor._node = out._node
        tensor.stop_gradient = out.stop_gradient
        return tensor
    hb = _host_backend(group)
    if hb is not None:   # host-mediated cross-process reduce
        res = hb.all_reduce(np.asarray(tensor.data), _OP_NAMES[op])
        tensor._data = jnp.asarray(res).astype(tensor.data.dtype)
        return tensor
    return tensor   # world_size == 1: identity


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Parity: c_reduce_* — on TPU SPMD all replicas hold the result; dst
    semantics preserved at the API level."""
    return all_reduce(tensor, op=op, group=group)


@_instrumented
def broadcast(tensor, src=0, group=None, sync_op=True, use_calc_stream=True):
    """Parity: c_broadcast. In SPMD: take src's shard via a masked psum."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        def fn(a):
            idx = _axis_index(axes)
            masked = jnp.where(idx == src, a, jnp.zeros_like(a))
            return lax.psum(masked, axes)
        out = run_op('c_broadcast', fn, [tensor])
        tensor._data = out._data
        tensor._node = out._node
        return tensor
    hb = _host_backend(group)
    if hb is not None:
        res = hb.broadcast(np.asarray(tensor.data), src=src)
        tensor._data = jnp.asarray(res).astype(tensor.data.dtype)
        return tensor
    return tensor


def _axis_index(axes):
    idx = lax.axis_index(axes[0])
    size_so_far = lax.axis_size(axes[0]) if hasattr(lax, 'axis_size') else \
        lax.psum(1, axes[0])
    for ax in axes[1:]:
        idx = idx * lax.psum(1, ax) + lax.axis_index(ax)
    return idx


@_instrumented
def all_gather(tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=True):
    """Parity: c_allgather → XLA AllGather. Appends per-rank shards to
    tensor_list (paddle list-out API)."""
    axes = _group_axes(group)
    n = get_world_size(group)
    if in_spmd_region() and axes:
        out = run_op('c_allgather',
                     lambda a: lax.all_gather(a, axes[0], tiled=False),
                     [tensor])
        from ..ops import manip
        shards = manip.unstack(out, axis=0)
        tensor_list.extend(shards)
        return tensor_list
    hb = _host_backend(group)
    if hb is not None:
        vals = hb.all_gather(np.asarray(tensor.data))
        tensor_list.extend(Tensor(jnp.asarray(v)) for v in vals)
        return tensor_list
    tensor_list.append(tensor)
    return tensor_list


@_instrumented
def all_gather_concat(tensor, axis=0, group=None):
    """XLA-native all_gather returning concatenated tensor (used by mp
    layers; parity with the c_concat op)."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        return run_op('c_concat',
                      lambda a: lax.all_gather(a, axes[0], axis=axis,
                                               tiled=True), [tensor])
    return tensor


@_instrumented
def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op=True):
    """Parity: c_reducescatter → XLA ReduceScatter."""
    axes = _group_axes(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..ops import manip
        src = manip.concat(list(src), axis=0)
    if in_spmd_region() and axes:
        out = run_op('c_reducescatter',
                     lambda a: lax.psum_scatter(a, axes[0], tiled=True),
                     [src])
        tensor._data = out._data
        tensor._node = out._node
        return tensor
    hb = _host_backend(group)
    if hb is not None:
        total = hb.all_reduce(np.asarray(src.data), _OP_NAMES[op])
        n = total.shape[0] // hb.world_size
        me = get_rank(group)
        tensor._data = jnp.asarray(total[me * n:(me + 1) * n])
        return tensor
    tensor._data = src._data
    return tensor


@_instrumented
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Parity: c_scatter — each rank takes its slice of src's tensor."""
    axes = _group_axes(group)
    if in_spmd_region() and axes and tensor_list is not None:
        from ..ops import manip
        full = manip.stack(tensor_list, axis=0)
        def fn(a):
            idx = _axis_index(axes)
            return jnp.take(a, idx, axis=0)
        out = run_op('c_scatter', fn, [full])
        tensor._data = out._data
        return tensor
    hb = _host_backend(group)
    if hb is not None:
        me = get_rank(group)
        if me == src:
            full = np.stack([np.asarray(t.data) for t in tensor_list])
        else:
            full = np.zeros((hb.world_size,) + tuple(tensor.data.shape),
                            dtype=np.asarray(tensor.data).dtype)
        got = hb.broadcast(full, src=src)
        tensor._data = jnp.asarray(got[me]).astype(tensor.data.dtype)
        return tensor
    if tensor_list is not None:
        tensor._data = tensor_list[src]._data
    return tensor


@_instrumented
def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """Parity: alltoall op → XLA AllToAll."""
    axes = _group_axes(group)
    from ..ops import manip
    if isinstance(in_tensor_list, Tensor):
        x = in_tensor_list
        split_concat = True
    else:
        x = manip.stack(list(in_tensor_list), axis=0)
        split_concat = False
    if in_spmd_region() and axes:
        out = run_op(
            'alltoall',
            lambda a: lax.all_to_all(a, axes[0], split_axis=0,
                                     concat_axis=0, tiled=split_concat),
            [x])
    else:
        hb = _host_backend(group)
        if hb is not None:
            me = get_rank(group)
            vals = hb.all_gather(np.asarray(x.data))   # [ws] of [ws, ...]
            if split_concat:
                n = vals[0].shape[0] // hb.world_size
                out = Tensor(jnp.concatenate(
                    [jnp.asarray(v[me * n:(me + 1) * n]) for v in vals]))
            else:
                out = Tensor(jnp.stack(
                    [jnp.asarray(v[me]) for v in vals]))
        else:
            out = x
    if out_tensor_list is not None:
        if split_concat:
            out_tensor_list.append(out)
        else:
            out_tensor_list.extend(manip.unstack(out, axis=0))
        return out_tensor_list
    return out


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    out = alltoall(in_tensor, None, group=group)
    if out_tensor is not None:
        out_tensor._data = out._data
        return out_tensor
    return out


@_instrumented
def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=True):
    """Parity: send_v2. Point-to-point send is inherently per-rank control
    flow; under single-controller SPMD one traced program runs on EVERY
    device, so "my rank" is not a Python constant (get_rank returns the
    host process rank) — use ppermute(tensor, pairs) / shift() with an
    explicit pair list instead (the pipeline engines do)."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        raise NotImplementedError(
            "standalone send() inside an SPMD region cannot infer the "
            "per-device source rank; use dist.ppermute(tensor, "
            f"[(src, {dst})], group) or dist.shift() with explicit pairs")
    return tensor


@_instrumented
def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=True):
    """Parity: recv_v2 — see send() for the SPMD p2p story."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        raise NotImplementedError(
            "standalone recv() inside an SPMD region cannot infer the "
            "per-device destination rank; use dist.ppermute(tensor, "
            f"[({src}, dst)], group) with explicit pairs")
    return tensor


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


@_instrumented
def ppermute(tensor, perm_pairs, group=None):
    """XLA collective-permute (ICI neighbor exchange) — the TPU replacement
    for NCCL p2p send/recv pairs (SURVEY.md §5.8)."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        return run_op('collective_permute',
                      lambda a: lax.ppermute(a, axes[0], perm_pairs),
                      [tensor])
    return tensor


@_instrumented
def shift(tensor, offset=1, group=None):
    """Ring shift along the group axis (pipeline/ring-attention building
    block)."""
    axes = _group_axes(group)
    if in_spmd_region() and axes:
        n = _axis_size(axes[0])
        pairs = [(i, (i + offset) % n) for i in range(n)]
        return ppermute(tensor, pairs, group)
    return tensor


def _axis_size(axis):
    from . import topology_runtime
    return topology_runtime.axis_size(axis)


# ---- model-parallel helper ops (collective.py:748-1283 parity) -------------
def _c_identity(tensor, group=None):
    """Identity fwd, allreduce bwd (column-parallel input)."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    @jax.custom_vjp
    def ident(a):
        return a

    def fwd(a):
        return a, None

    def bwd(_, ct):
        return (lax.psum(ct, axes),)
    ident.defvjp(fwd, bwd)
    return run_op('c_identity', ident, [tensor])


def _mp_allreduce(tensor, group=None):
    """Allreduce fwd, identity bwd (row-parallel output)."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    @jax.custom_vjp
    def mp_ar(a):
        return lax.psum(a, axes)

    def fwd(a):
        return lax.psum(a, axes), None

    def bwd(_, ct):
        return (ct,)
    mp_ar.defvjp(fwd, bwd)
    return run_op('mp_allreduce_sum', mp_ar, [tensor])


def _c_concat(tensor, group=None):
    """All-gather along last dim (parity: c_concat op)."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor
    return run_op('c_concat',
                  lambda a: lax.all_gather(a, axes[0], axis=a.ndim - 1,
                                           tiled=True), [tensor])


def _c_split(tensor, group=None):
    """Keep only this rank's slice of the last dim (parity: c_split op)."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    def fn(a):
        n = lax.psum(1, axes[0])
        idx = lax.axis_index(axes[0])
        size = a.shape[-1] // n
        return lax.dynamic_slice_in_dim(a, idx * size, size, axis=a.ndim - 1)
    return run_op('c_split', fn, [tensor])


# ---- sequence-parallel activation sharding (Megatron-SP, ISSUE 12) ---------
# The LayerNorm/dropout/residual segments between mp regions are
# token-local, so they can run on sequence slices scattered over the mp
# group: the row-parallel allreduce becomes a psum_scatter along the
# token dim (same wire bytes, 1/mp resident activation bytes in the
# segment), and the next column-parallel input all_gathers back. Both
# primitives are jax-transposable (RS <-> AG), so grads are identical to
# the allreduce path (tests/test_remat.py pins loss AND per-device grads
# against the replicated route).

def _seq_axis(tensor):
    """Token dim of an activation: axis 1 for [B, L, H], axis 0 for
    unbatched [L, H]."""
    return 1 if tensor.ndim >= 3 else 0


def _c_reduce_scatter_seq(tensor, group=None):
    """Row-parallel output under sequence parallelism: sum over the mp
    group, each rank keeping its token slice of the full sum."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    def fn(a):
        return lax.psum_scatter(a, axes, scatter_dimension=_seq_axis(a),
                                tiled=True)
    return run_op('c_reduce_scatter_seq', fn, [tensor])


def _c_allgather_seq(tensor, group=None):
    """Column-parallel input under sequence parallelism: rebuild the full
    token dim from the scattered slices (transpose of the RS above)."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    def fn(a):
        ax = _seq_axis(a)
        out = a
        for name in reversed(axes):
            out = lax.all_gather(out, name, axis=ax, tiled=True)
        return out
    return run_op('c_allgather_seq', fn, [tensor])


def _c_slice_seq(tensor, group=None):
    """This rank's token slice of a REPLICATED activation (entry into a
    sequence-parallel segment from replicated compute — e.g. the
    embedding output): a static slice, no forward wire traffic.

    Custom VJP: the backward all_gathers the cotangent slices back to
    the full token dim, so everything upstream (embedding tables) sees
    the SAME full-token cotangent it sees on the replicated route — the
    default slice transpose would zero out the other ranks' tokens and
    starve the embedding grads of 1-1/mp of the batch."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    @jax.custom_vjp
    def slice_seq(a):
        return _slice_local(a, axes)

    def fwd(a):
        return _slice_local(a, axes), None

    def bwd(_, ct):
        ax = _seq_axis(ct)
        out = ct
        for name in reversed(axes):
            out = lax.all_gather(out, name, axis=ax, tiled=True)
        return (out,)
    slice_seq.defvjp(fwd, bwd)
    return run_op('c_slice_seq', slice_seq, [tensor])


def _slice_local(a, axes):
    ax = _seq_axis(a)
    n = 1
    idx = 0
    for name in axes:      # outer-to-inner, the tuple-axis order
        n = n * lax.psum(1, name)
        idx = idx * lax.psum(1, name) + lax.axis_index(name)
    L = a.shape[ax]
    if L % int(n) != 0:
        raise ValueError(
            f"sequence length {L} does not divide the "
            f"sequence-parallel group size {int(n)} (axes {axes})")
    size = L // int(n)
    return lax.dynamic_slice_in_dim(a, idx * size, size, axis=ax)


def _c_gather_seq_replicated(tensor, group=None):
    """Exit of the sequence-parallel region back into REPLICATED compute
    (the final-norm → LM-head boundary): all_gather forward, and a
    custom backward that takes this rank's token SLICE of the cotangent.
    The replicated downstream hands every rank the same full-token
    cotangent, so slicing is its exact inverse; the default
    psum_scatter transpose would over-count it by the group size."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        return tensor

    @jax.custom_vjp
    def gather_seq(a):
        return _gather_full(a, axes)

    def fwd(a):
        return _gather_full(a, axes), None

    def bwd(_, ct):
        return (_slice_local(ct, axes),)
    gather_seq.defvjp(fwd, bwd)
    return run_op('c_gather_seq_replicated', gather_seq, [tensor])


def _gather_full(a, axes):
    ax = _seq_axis(a)
    out = a
    for name in reversed(axes):
        out = lax.all_gather(out, name, axis=ax, tiled=True)
    return out


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  ignore_index=-100):
    """Vocab-parallel softmax CE (parity: c_softmax_with_cross_entropy op).
    logits are sharded on the class dim across the group axis."""
    axes = _group_axes(group)
    if not (in_spmd_region() and axes):
        from ..ops import nn_ops
        return nn_ops.softmax_with_cross_entropy(logits, label)

    def fn(lg, lb):
        part = lg.shape[-1]
        idx = lax.axis_index(axes[0])
        vocab_start = idx * part
        # global max for stability (shift-invariant → safe to stop-grad,
        # and pmax has no AD rule)
        local_max = lax.stop_gradient(jnp.max(lg, axis=-1, keepdims=True))
        gmax = lax.pmax(local_max, axes)
        shifted = lg - gmax
        sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True),
                          axes)
        logZ = jnp.log(sumexp)
        lb_local = lb - vocab_start
        in_range = (lb_local >= 0) & (lb_local < part)
        safe = jnp.clip(lb_local, 0, part - 1)
        picked = jnp.take_along_axis(shifted, safe[..., None], axis=-1)
        picked = jnp.where(in_range[..., None], picked, 0.0)
        picked = lax.psum(picked, axes)
        loss = logZ - picked
        # ignored labels: zero loss and (via where's masked vjp) zero grad
        ignored = (lb == ignore_index)[..., None]
        loss = jnp.where(ignored, 0.0, loss)
        return loss.reshape(lb.shape + (1,))
    return run_op('c_softmax_with_cross_entropy', fn, [logits, label],
                  n_nondiff=1)


def _c_embedding(weight, x, start_index=None, group=None):
    """Row-sharded embedding lookup (parity: c_embedding op). When
    start_index is None it is derived from the rank's position on the group
    axis × local rows (the shard_map local-view convention)."""
    axes = _group_axes(group)

    def fn(w, idx):
        rows = w.shape[0]
        if start_index is None and in_spmd_region() and axes:
            start = _axis_index(axes) * rows
        else:
            start = start_index or 0
        local = idx - start
        in_range = (local >= 0) & (local < rows)
        safe = jnp.clip(local, 0, rows - 1)
        out = jnp.take(w, safe, axis=0)
        out = jnp.where(in_range[..., None], out, 0.0)
        if in_spmd_region() and axes:
            out = lax.psum(out, axes)
        return out
    return run_op('c_embedding', fn, [weight, x], n_nondiff=1)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Parity: paddle.distributed.split:1283 — auto row/column-parallel
    Linear / Embedding. Returns the layer output; the underlying sharded
    layers live in fleet.meta_parallel.parallel_layers."""
    from .fleet.meta_parallel.parallel_layers import mp_layers
    if operation == 'linear':
        if axis == 0:
            layer = mp_layers.RowParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=False)
        else:
            layer = mp_layers.ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                has_bias=bias_attr is not False, gather_output=gather_out)
        return layer(x)
    if operation == 'embedding':
        layer = mp_layers.VocabParallelEmbedding(size[0], size[1],
                                                 weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation}")
