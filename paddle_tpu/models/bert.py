"""BERT (BASELINE config 3: BERT-base pretraining with bf16 + ZeRO-2).

Reference parity: the transformer encoder stack the reference builds from
nn/layer/transformer.py (TransformerEncoder:622) with MLM+NSP pretraining
heads, trained via fleet sharding (dist_sharding tests pattern).
"""
import math

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..ops import math as M
from ..ops import manip
from ..ops import nn_ops as F
from ..nn import initializer as I


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_seq_len=512,
                 type_vocab_size=2, hidden_dropout=0.1, attn_dropout=0.1,
                 initializer_range=0.02, layer_norm_eps=1e-12,
                 mlm_loss_chunks=16):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout = hidden_dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        # fused-xent chunk count (16 measured fastest at B=64,L=512 on v5e)
        self.mlm_loss_chunks = mlm_loss_chunks


class BertEmbeddings(nn.Layer):
    def __init__(self, config):
        super().__init__()
        init = nn.ParamAttr(
            initializer=I.Normal(0.0, config.initializer_range))
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size,
                                            weight_attr=init)
        self.position_embeddings = nn.Embedding(config.max_seq_len,
                                                config.hidden_size,
                                                weight_attr=init)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size,
                                                  weight_attr=init)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        L = input_ids.shape[-1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(L, dtype=jnp.int32))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros(input_ids.shape, jnp.int32))
        x = M.add(M.add(self.word_embeddings(input_ids),
                        self.position_embeddings(position_ids)),
                  self.token_type_embeddings(token_type_ids))
        # remat boundary (docs/performance.md#remat-policy): saved under
        # attn_mlp_boundaries so the backward never replays the three
        # embedding gathers; the LN/dropout tail recomputes
        from ..distributed.fleet.utils.recompute import tag_tensor
        return self.dropout(self.layer_norm(
            tag_tensor(x, 'embed_out')))


class BertModel(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        encoder_layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_heads, config.intermediate_size,
            dropout=config.hidden_dropout, activation='gelu',
            attn_dropout=config.attn_dropout)
        self.encoder = nn.TransformerEncoder(encoder_layer,
                                             config.num_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            mask = manip.unsqueeze(attention_mask, [1, 2])
            attention_mask = M.scale(M.subtract(
                Tensor(jnp.asarray(1.0)), mask.astype('float32')), -1e9)
        x = self.encoder(x, attention_mask)
        pooled = M.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads."""

    def __init__(self, config):
        super().__init__()
        self.bert = BertModel(config)
        self.config = config
        self.mlm_transform = nn.Linear(config.hidden_size,
                                       config.hidden_size)
        self.mlm_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_eps)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_label=None):
        """Without labels: returns (mlm_logits, nsp_logits). With labels:
        returns the pretraining loss, computed through the chunked fused
        projection-xent so the [B*L, vocab] logits never materialize."""
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        w = self.bert.embeddings.word_embeddings.weight
        nsp_logits = self.nsp(pooled)
        if masked_lm_labels is not None:
            mlm = F.fused_linear_cross_entropy(
                h, w, masked_lm_labels, ignore_index=-100,
                chunks=self.config.mlm_loss_chunks)
            if next_sentence_label is None:
                return mlm
            nsp = F.cross_entropy(nsp_logits, next_sentence_label)
            return M.add(mlm, nsp)
        mlm_logits = M.matmul(h, w, transpose_y=True)
        return mlm_logits, nsp_logits


def bert_pretrain_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                       ignore_index=-100):
    mlm = F.cross_entropy(mlm_logits, mlm_labels,
                          ignore_index=ignore_index)
    nsp = F.cross_entropy(nsp_logits, nsp_labels)
    return M.add(mlm, nsp)
