"""DeepFM (BASELINE config 5 family, next to wide_deep).

Reference parity: the DeepFM topology the reference's PS configs train —
shared sparse embeddings feeding (a) a first-order linear term, (b) the
factorization-machine second-order interaction, (c) a deep MLP tower
(the CTR model family of the heterPS/pscore examples).

TPU-native: the FM pairwise interaction uses the sum-square trick (one
reduction, no O(F^2) loop); sparse lookups ride the same
DistributedEmbedding tape integration wide_deep uses, so the model runs
against the in-process host table or the remote PS unchanged.
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..core.autograd import run_op
from ..ops import math as M
from ..ops import manip
from ..ops import nn_ops as F


class DeepFM(nn.Layer):
    """fields: number of sparse fields; each sample carries one feature id
    per field (the classic Criteo layout)."""

    def __init__(self, num_features=1000, fields=10, embed_dim=8,
                 hidden=(32, 16), use_ps=False):
        super().__init__()
        self.fields = fields
        self.embed_dim = embed_dim
        if use_ps:
            from ..distributed.ps.embedding import DistributedEmbedding
            self.embedding = DistributedEmbedding(num_features, embed_dim)
            self.linear_embedding = DistributedEmbedding(num_features, 1)
        else:
            self.embedding = nn.Embedding(num_features, embed_dim)
            self.linear_embedding = nn.Embedding(num_features, 1)
        self.bias = self.create_parameter([1], is_bias=True)
        mlp = []
        d = fields * embed_dim
        for h in hidden:
            mlp += [nn.Linear(d, h), nn.ReLU()]
            d = h
        mlp.append(nn.Linear(d, 1))
        self.mlp = nn.Sequential(*mlp)

    def forward(self, feat_ids):
        """feat_ids [N, fields] int → logits [N, 1]."""
        emb = self.embedding(feat_ids)                  # [N, F, D]
        first = manip.reshape(self.linear_embedding(feat_ids),
                              [feat_ids.shape[0], self.fields])
        first = M.sum(first, axis=1, keepdim=True)      # [N, 1]

        def fm(e):
            # 0.5 * ((Σ v)^2 − Σ v^2) summed over D — sum-square trick
            s = e.sum(1)
            return (0.5 * (s * s - (e * e).sum(1))).sum(-1,
                                                        keepdims=True)
        second = run_op('fm_interaction', fm, [emb])
        deep = self.mlp(manip.reshape(
            emb, [feat_ids.shape[0], self.fields * self.embed_dim]))
        return M.add(M.add(M.add(first, second), deep), self.bias)


def deepfm_loss(logits, labels):
    return F.binary_cross_entropy_with_logits(
        logits, labels.astype('float32'))
