"""Wide&Deep — BASELINE config 5 (trillion-param sparse PS + dense TPU).

Reference parity: the canonical PS-mode ranking model the reference's
parameter-server stack trains (a_sync strategy + distributed_lookup_table);
sparse side rides paddle_tpu.distributed.ps (host tables), dense towers run
on TPU.
"""
import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..ops import math as M
from ..ops import manip
from ..ops import nn_ops as F
from ..distributed.ps.embedding import DistributedEmbedding


class WideDeep(nn.Layer):
    def __init__(self, sparse_feature_dim=16, num_sparse_slots=8,
                 dense_dim=13, hidden_sizes=(64, 32), a_sync=False,
                 sparse_lr=0.05, mode=None, geo_k=10):
        super().__init__()
        self.num_sparse_slots = num_sparse_slots
        self.embedding = DistributedEmbedding(
            sparse_feature_dim, optimizer='adagrad',
            learning_rate=sparse_lr, a_sync=a_sync, mode=mode, geo_k=geo_k)
        # wide part: per-feature scalar weights from a second tiny table
        self.wide_embedding = DistributedEmbedding(
            1, optimizer='sgd', learning_rate=sparse_lr, a_sync=a_sync,
            mode=mode, geo_k=geo_k)
        layers = []
        in_dim = dense_dim + num_sparse_slots * sparse_feature_dim
        for h in hidden_sizes:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.deep = nn.Sequential(*layers)

    def forward(self, sparse_ids, dense_feats):
        """sparse_ids: int64 [B, num_slots]; dense_feats: [B, dense_dim]."""
        emb = self.embedding(sparse_ids)          # B, S, D
        emb_flat = manip.reshape(
            emb, [emb.shape[0], emb.shape[1] * emb.shape[2]])
        deep_in = manip.concat([dense_feats, emb_flat], axis=1)
        deep_out = self.deep(deep_in)             # B, 1
        wide = self.wide_embedding(sparse_ids)    # B, S, 1
        wide_out = M.sum(wide, axis=[1])          # B, 1
        return M.add(deep_out, wide_out)

    def loss(self, logits, labels):
        return F.binary_cross_entropy_with_logits(
            logits, labels.astype('float32'))
