"""GPT — the flagship model (BASELINE config 4: GPT-3 1.3B hybrid parallel).

Reference parity: the GPT used by sandyhouse/Paddle's fleet hybrid-parallel
stack (the pipeline/sharding meta-optimizers were built to train it;
test models: fluid/tests/unittests/hybrid_parallel_pp_transformer.py,
hybrid_parallel_mp_layers.py patterns).

TPU-native: decoder blocks are built from the tensor-parallel layers
(VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear), so under
the hybrid engine's shard_map the qkv/ffn matmuls run on mp-local shards with
XLA collectives between them — Megatron semantics on ICI. Attention uses one
fused softmax(QK^T)V with a causal mask in-kernel (MXU-shaped batched
matmuls); the Pallas flash-attention kernel swaps in for long sequences.
All shapes static; dropout keys via the global RNG stream.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..core.autograd import run_op
from ..ops import math as M
from ..ops import manip
from ..ops import nn_ops as F
from ..nn import initializer as I
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy, _mp_info)
from ..distributed.fleet.utils.recompute import tag_tensor as _remat_tag


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=None, max_seq_len=1024,
                 hidden_dropout=0.1, attn_dropout=0.1,
                 initializer_range=0.02, layer_norm_eps=1e-5,
                 use_flash_attention=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.hidden_dropout = hidden_dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.use_flash_attention = use_flash_attention


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                     num_heads=4, max_seq_len=256, **kw)


def gpt_small(**kw):  # GPT-2 124M
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt_medium(**kw):  # 350M
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16, **kw)


def gpt_1p3b(**kw):  # GPT-3 1.3B (BASELINE config 4)
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


def _sp_active():
    """True only when the engine declared the batch sequence-sharded over a
    live 'sp' axis (mere axis presence is not enough — e.g. the pipeline
    engine runs with the full mesh in scope but dp-only batch sharding)."""
    from ..distributed import collective as C
    from ..distributed import topology_runtime
    return (C.in_spmd_region() and C.sp_data_sharded()
            and 'sp' in C.current_spmd_axes()
            and topology_runtime.axis_size('sp') > 1)


def _mp_seq_active():
    """True when the engine declared Megatron-style sequence-parallel
    activation sharding: the residual stream between mp regions runs on
    token slices scattered over the mp group
    (docs/performance.md#sequence-parallel-activations)."""
    from ..distributed import collective as C
    return C.in_spmd_region() and C.mp_seq_sharded()


class GPTEmbeddings(nn.Layer):
    """Token (vocab-parallel) + learned position embeddings. Under sequence
    parallelism the local chunk's positions are offset by the sp rank."""

    def __init__(self, config):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init))
        self.position_embeddings = nn.Embedding(
            config.max_seq_len, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init))
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            L = input_ids.shape[-1]
            pos = jnp.arange(L, dtype=jnp.int32)
            if _sp_active():
                from jax import lax
                pos = pos + lax.axis_index('sp') * L
            position_ids = Tensor(pos)
        tok = self.word_embeddings(input_ids)
        pos = self.position_embeddings(position_ids)
        return self.dropout(
            _remat_tag(M.add(tok, pos), 'embed_out'))


class GPTAttention(nn.Layer):
    """Causal self-attention, heads sharded over mp.

    qkv = ColumnParallel (gather_output=False) so each mp rank holds
    nh/mp heads; out proj = RowParallel(input_is_parallel) — one allreduce
    per attention block, Megatron-style.
    """

    def __init__(self, config):
        super().__init__()
        self.world_size, _, _ = _mp_info()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.local_heads = config.num_heads // self.world_size
        self.attn_dropout_p = config.attn_dropout
        self.use_flash = config.use_flash_attention
        init = I.Normal(0.0, config.initializer_range)
        out_init = I.Normal(
            0.0, config.initializer_range / math.sqrt(2 * config.num_layers))
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=init), gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=out_init),
            input_is_parallel=True)
        # NOTE: the hidden dropout that used to follow out_proj now
        # lives in GPTDecoderLayer's residual join (F.dropout_add), so
        # it fuses with the add; the RNG draw order is unchanged.

    def forward(self, x, cache=None, cache_len=None):
        """cache: optional (k, v) Tensors [B, nh, max_len, hd] (fixed-size,
        position-indexed by cache_len) enabling O(1)-per-token decode."""
        if cache is not None:
            return self._forward_cached(x, cache, cache_len)
        # remat boundary tags (docs/performance.md#remat-policy): the
        # attn_mlp_boundaries policy saves these contraction outputs and
        # recomputes the cheap elementwise chains between them
        qkv = _remat_tag(self.qkv_proj(x), 'attn_qkv')
        # under sequence-parallel activation sharding the input x is a
        # token SLICE and qkv_proj gathered it back to the full token
        # dim — take B/L from qkv, not x
        B, L = qkv.shape[0], qkv.shape[1]
        hd, nh = self.head_dim, qkv.shape[-1] // (3 * self.head_dim)

        # out-dim layout is (head, 3, hd): column-sharding then hands each
        # mp rank whole heads (Megatron qkv packing), so TP == dense.
        attn_key = None
        if self.attn_dropout_p > 0.0 and self.training:
            from ..core import rng as _rng
            attn_key = _rng.next_key()

        def attn(a):
            x5 = a.reshape(B, L, nh, 3, hd)
            q, k, v = x5[:, :, :, 0], x5[:, :, :, 1], x5[:, :, :, 2]
            q = q.transpose(0, 2, 1, 3)  # B, nh, L, hd
            k = k.transpose(0, 2, 1, 3)
            v = v.transpose(0, 2, 1, 3)
            scores = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                                preferred_element_type=jnp.float32)
            scores = scores * (1.0 / math.sqrt(hd))
            causal = jnp.tril(jnp.ones((L, L), bool))
            scores = jnp.where(causal, scores, jnp.asarray(-1e9, scores.dtype))
            probs = jax.nn.softmax(scores, axis=-1).astype(a.dtype)
            if attn_key is not None:
                keep = jax.random.bernoulli(
                    attn_key, 1.0 - self.attn_dropout_p, probs.shape)
                probs = jnp.where(keep,
                                  probs / (1.0 - self.attn_dropout_p), 0.0)
            out = jnp.einsum('bhqk,bhkd->bhqd', probs, v)
            return out.transpose(0, 2, 1, 3).reshape(B, L, nh * hd)

        if _sp_active():
            # sequence-parallel: K/V ring over the 'sp' axis (net-new vs the
            # reference — SURVEY.md §5.7)
            from ..ops import ring_attention as ra
            from ..distributed import topology_runtime
            ctx = ra.ring_causal_qkv(qkv, nh, hd, axis_name='sp',
                                     sp=topology_runtime.axis_size('sp'),
                                     dropout=self.attn_dropout_p
                                     if self.training else 0.0)
        elif self.use_flash and L >= 512:
            # active attention dropout no longer forces the dense path:
            # the keep mask is drawn OUTSIDE the kernel at the exact
            # RNG-stream point the dense path draws (attn_key above), so
            # the dropout-fused flash route is same-seed/same-mask
            # comparable with the dense reference (ISSUE 12)
            from ..ops.pallas import flash_attention as fa
            ctx = fa.causal_attention(
                qkv, nh, hd,
                dropout=self.attn_dropout_p if attn_key is not None
                else 0.0,
                dropout_key=attn_key)
        else:
            from ..ops.pallas import scaffold as _scaffold
            _scaffold.record_route('flash_dropout' if attn_key is not None
                                   else 'flash_attention', False)
            ctx = run_op('fused_attention', attn, [qkv])
        ctx = _remat_tag(ctx, 'attn_ctx')
        out = _remat_tag(self.out_proj(ctx), 'attn_out')
        return out

    def _forward_cached(self, x, cache, cache_len):
        """Single-step decode: x [B, 1, H]; write this token's k/v at
        position cache_len, attend over cache[:cache_len+1]."""
        B, L, _ = x.shape
        qkv = self.qkv_proj(x)
        hd = self.head_dim
        nh = qkv.shape[-1] // (3 * hd)
        k_cache, v_cache = cache
        pos = cache_len.data if isinstance(cache_len, Tensor) else cache_len

        def fn(a, kc, vc):
            x5 = a.reshape(B, L, nh, 3, hd)
            q = x5[:, :, :, 0].transpose(0, 2, 1, 3)  # B,nh,1,hd
            k = x5[:, :, :, 1].transpose(0, 2, 1, 3)
            v = x5[:, :, :, 2].transpose(0, 2, 1, 3)
            kc2 = jax.lax.dynamic_update_slice(
                kc, k.astype(kc.dtype), (0, 0, pos, 0))
            vc2 = jax.lax.dynamic_update_slice(
                vc, v.astype(vc.dtype), (0, 0, pos, 0))
            scores = jnp.einsum('bhqd,bhkd->bhqk', q,
                                kc2.astype(q.dtype),
                                preferred_element_type=jnp.float32)
            scores = scores * (1.0 / math.sqrt(hd))
            idx = jnp.arange(kc.shape[2])
            mask = idx[None, None, None, :] <= pos
            scores = jnp.where(mask, scores, -1e9)
            probs = jax.nn.softmax(scores, axis=-1).astype(a.dtype)
            o = jnp.einsum('bhqk,bhkd->bhqd', probs, vc2.astype(a.dtype))
            o = o.transpose(0, 2, 1, 3).reshape(B, L, nh * hd)
            return o, kc2, vc2
        ctx, kc2, vc2 = run_op('cached_attention', fn,
                               [qkv, k_cache, v_cache])
        out = self.out_proj(ctx)
        return out, (kc2, vc2)

    def forward_paged(self, x, kv, page_tables, seq_lens, q_lens):
        """Serving-engine path: x [B, T, H] (T new tokens per row,
        right-padded to q_lens); kv = (k_pages, v_pages) Tensors
        [num_pages, page_size, local_heads*hd] from the shared pool —
        or the int8 pool's 4-tuple (k_pages, v_pages, k_scales,
        v_scales), in which case new K/V quantize at scatter time and
        attention dequantizes inside the kernel (kv_dtype='int8',
        docs/serving.md#quantized-kv). Writes the new tokens' k/v into
        the sequences' pages and runs ragged paged attention over each
        row's page table (causal within the sequence).
        page_tables/seq_lens/q_lens are plain int32 arrays (non-diff,
        captured like cache_len above)."""
        B, T, _ = x.shape
        qkv = self.qkv_proj(x)
        hd = self.head_dim
        nh = qkv.shape[-1] // (3 * hd)
        from ..ops.pallas import paged_attention as pa

        def _split(a):
            x5 = a.reshape(B, T, nh, 3, hd)
            return (x5[:, :, :, 0].reshape(B, T, nh * hd),
                    x5[:, :, :, 1].reshape(B, T, nh * hd),
                    x5[:, :, :, 2].reshape(B, T, nh * hd))

        if len(kv) == 4:
            k_pages, v_pages, k_scales, v_scales = kv

            def fnq(a, kp, vp, ks, vs):
                q, k, v = _split(a)
                kp2, vp2, ks2, vs2 = pa.write_kv_pages_quantized(
                    kp, vp, ks, vs, k, v, page_tables, seq_lens,
                    q_lens, num_heads=nh)
                ctx = pa.ragged_paged_attention(
                    q, kp2, vp2, page_tables, seq_lens, q_lens,
                    num_heads=nh, head_dim=hd, k_scales=ks2,
                    v_scales=vs2)
                return ctx, kp2, vp2, ks2, vs2
            ctx, kp2, vp2, ks2, vs2 = run_op(
                'paged_attention', fnq,
                [qkv, k_pages, v_pages, k_scales, v_scales])
            out = self.out_proj(ctx)
            return out, (kp2, vp2, ks2, vs2)

        k_pages, v_pages = kv

        def fn(a, kp, vp):
            q, k, v = _split(a)
            kp2, vp2 = pa.write_kv_pages(kp, vp, k, v, page_tables,
                                         seq_lens, q_lens)
            ctx = pa.ragged_paged_attention(
                q, kp2, vp2, page_tables, seq_lens, q_lens,
                num_heads=nh, head_dim=hd)
            return ctx, kp2, vp2
        ctx, kp2, vp2 = run_op('paged_attention', fn,
                               [qkv, k_pages, v_pages])
        out = self.out_proj(ctx)
        return out, (kp2, vp2)


class GPTMLP(nn.Layer):
    """FFN. The fc1 bias-add fuses into the GELU (F.bias_gelu — the
    Pallas bias+GELU kernel on TPU, the identical jnp expression on
    CPU), and the trailing hidden dropout moved UP into the decoder
    layer's residual join (F.dropout_add) so it fuses with the add —
    same ops, same RNG draw order, kernel-fusable boundaries."""

    def __init__(self, config):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        out_init = I.Normal(
            0.0, config.initializer_range / math.sqrt(2 * config.num_layers))
        self.fc1 = ColumnParallelLinear(
            config.hidden_size, config.ffn_hidden_size,
            weight_attr=nn.ParamAttr(initializer=init), gather_output=False)
        self.fc2 = RowParallelLinear(
            config.ffn_hidden_size, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=out_init),
            input_is_parallel=True)

    def forward(self, x):
        if self.fc1.bias is not None:
            h = F.bias_gelu(
                _remat_tag(self.fc1(x, with_bias=False),
                                  'mlp_fc1'),
                self.fc1.bias, approximate=True)
        else:
            h = F.gelu(_remat_tag(self.fc1(x), 'mlp_fc1'),
                       approximate=True)
        return _remat_tag(self.fc2(h), 'mlp_out')


class GPTDecoderLayer(nn.Layer):
    """Pre-LN transformer block. Both residual joins run through
    F.dropout_add (the sublayers' trailing hidden dropout fused with
    the residual add — one Pallas pass on TPU, the identical dropout →
    add expression and RNG stream on the reference route; eval and
    dropout=0 degrade to the plain add)."""

    def __init__(self, config):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)
        self.hidden_dropout = config.hidden_dropout
        # params consumed while the residual stream is sequence-
        # scattered (docs/performance.md#sequence-parallel-activations):
        # their per-rank grads cover only the local token slice, so the
        # engine psums them over 'mp' when sequence_parallel is on
        # (Megatron marks its LN params the same way). Inert otherwise.
        for p in (list(self.ln1.parameters()) + list(self.ln2.parameters())
                  + ([self.attn.out_proj.bias]
                     if self.attn.out_proj.bias is not None else [])
                  + ([self.mlp.fc2.bias]
                     if self.mlp.fc2.bias is not None else [])):
            p.sequence_parallel_grad = True

    def _join(self, sub_out, residual):
        return F.dropout_add(sub_out, residual, p=self.hidden_dropout,
                             training=self.training)

    def forward(self, x, cache=None, cache_len=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln1(x), cache=cache,
                                     cache_len=cache_len)
            x = self._join(a, x)
            x = self._join(self.mlp(self.ln2(x)), x)
            return x, new_cache
        x = self._join(self.attn(self.ln1(x)), x)
        x = self._join(self.mlp(self.ln2(x)), x)
        return x

    def forward_paged(self, x, kv, page_tables, seq_lens, q_lens):
        a, new_kv = self.attn.forward_paged(self.ln1(x), kv,
                                            page_tables, seq_lens,
                                            q_lens)
        x = self._join(a, x)
        x = self._join(self.mlp(self.ln2(x)), x)
        return x, new_kv


class GPTModel(nn.Layer):
    _supports_sequence_parallel = True

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_layers)])
        self.final_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        for p in self.final_norm.parameters():
            # the final norm also runs on the scattered stream
            p.sequence_parallel_grad = True

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_len=None):
        x = self.embeddings(input_ids, position_ids)
        if caches is not None:
            new_caches = []
            for layer, c in zip(self.layers, caches):
                x, nc = layer(x, cache=c, cache_len=cache_len)
                new_caches.append(nc)
            return self.final_norm(x), new_caches
        qkv = self.layers[0].attn.qkv_proj if self.layers else None
        seqp = (_mp_seq_active() and qkv is not None
                and qkv.world_size > 1)
        if seqp:
            # sequence-parallel activation sharding: the residual
            # stream drops to this rank's token slice here (a static
            # slice — the embed output is replicated over mp) and stays
            # scattered through every LayerNorm/dropout/residual
            # segment; the qkv/fc1 entries gather, the out-proj/fc2
            # exits re-scatter (mp_layers), and the stream is gathered
            # back to full ONLY after the final norm below.
            from ..distributed import collective as C
            x = C._c_slice_seq(x, group=qkv.group)
        for layer in self.layers:
            x = layer(x)
        x = self.final_norm(x)
        if seqp:
            from ..distributed import collective as C
            x = C._c_gather_seq_replicated(x, group=qkv.group)
        return x

    def forward_paged(self, input_ids, position_ids, kv_list,
                      page_tables, seq_lens, q_lens):
        """Serving-engine forward over the paged KV pool: kv_list is the
        per-layer [(k_pages, v_pages)] Tensors; returns (hidden,
        new_kv_list). See serving/engine.py for the step around it."""
        x = self.embeddings(input_ids, position_ids)
        new_kv = []
        for layer, c in zip(self.layers, kv_list):
            x, nc = layer.forward_paged(x, c, page_tables, seq_lens,
                                        q_lens)
            new_kv.append(nc)
        return self.final_norm(x), new_kv

    def init_caches(self, batch, max_len, dtype=None):
        import jax.numpy as _jnp
        cfg = self.config
        hd = cfg.hidden_size // cfg.num_heads
        nh_local = self.layers[0].attn.local_heads
        dt = dtype or self.embeddings.word_embeddings.weight.dtype
        return [(Tensor(_jnp.zeros((batch, nh_local, max_len, hd), dt)),
                 Tensor(_jnp.zeros((batch, nh_local, max_len, hd), dt)))
                for _ in range(cfg.num_layers)]


class GPTForCausalLM(nn.Layer):
    """LM head tied to the (vocab-parallel) input embedding — parity with
    the SharedLayerDesc tying in the reference's pipeline GPT (A.4)."""

    _supports_sequence_parallel = True

    def __init__(self, config):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config

    def forward(self, input_ids, position_ids=None):
        hidden = self.gpt(input_ids, position_ids)
        # Megatron "copy to tensor-parallel region" (f op) in front of
        # the vocab-parallel head matmul: identity forward, psum('mp')
        # backward. Without it each mp rank's backward carries only its
        # own vocab shard's PARTIAL cotangent into final_norm and the
        # last decoder segment, so replicated-param grads there diverge
        # per rank (ColumnParallelLinear heads get this via their own
        # _c_identity; the tied-matmul path was missing it).
        from ..distributed import collective as C
        if self.gpt.embeddings.word_embeddings.world_size > 1 \
                and C.in_spmd_region():
            hidden = C._c_identity(
                hidden, group=self.gpt.embeddings.word_embeddings.group)
        w = self.gpt.embeddings.word_embeddings.weight  # [V(/mp local), H]
        logits = M.matmul(hidden, w, transpose_y=True)
        return logits  # class dim vocab-parallel under mp

    @staticmethod
    def _sample_next(step_logits, temperature, top_k):
        import numpy as np_
        step = step_logits / max(temperature, 1e-6)
        if top_k and top_k > 0:
            kth = np_.sort(step, axis=-1)[:, -top_k][:, None]
            z = np_.where(step < kth, -1e30, step)
            z = z - z.max(-1, keepdims=True)
            p = np_.exp(z) / np_.exp(z).sum(-1, keepdims=True)
            return np_.asarray(
                [np_.random.choice(p.shape[-1], p=row) for row in p])
        return step.argmax(-1)

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=0, eos_token_id=None, use_cache=True):
        """Greedy / top-k sampling decode (parity role: the beam_search/
        sampling ops tier). use_cache=True runs the O(1)-per-token KV-cached
        path with a jitted fixed-shape decode step; False re-forwards the
        full window per token."""
        ids_probe = input_ids.data if isinstance(input_ids, Tensor) \
            else input_ids
        fits = (ids_probe.shape[-1] + max_new_tokens
                <= self.config.max_seq_len)
        if use_cache and fits:
            return self._generate_cached(input_ids, max_new_tokens,
                                         temperature, top_k, eos_token_id)
        # beyond max_seq_len the cached path would truncate; the sliding-
        # window re-forward below matches the uncached semantics exactly
        import numpy as np_
        from ..core import rng as rng_mod
        from ..core.autograd import no_grad
        ids = np_.asarray(input_ids.data if isinstance(input_ids, Tensor)
                          else input_ids)
        # early-exit once EVERY row has emitted EOS at least once (rows
        # that finish early keep emitting until the laggards catch up,
        # so the tokens that ARE emitted are step-for-step identical to
        # the run-to-max_new_tokens output)
        done = np_.zeros(ids.shape[0], bool)
        with no_grad():
            for _ in range(max_new_tokens):
                window = ids[:, -self.config.max_seq_len:]
                logits = self(Tensor(window.astype('int32')))
                nxt = self._sample_next(np_.asarray(logits.data)[:, -1, :],
                                        temperature, top_k)
                ids = np_.concatenate([ids, nxt[:, None]], axis=1)
                if eos_token_id is not None:
                    done |= (nxt == eos_token_id)
                    if done.all():
                        break
        return Tensor(ids)

    def generate_batch(self, prompts, max_new_tokens=32, temperature=1.0,
                       top_k=0, eos_token_id=None, serving_config=None,
                       engine=None, **engine_kw):
        """Continuous-batching decode over the serving engine: `prompts`
        is a LIST of ragged token-id sequences (mixed lengths welcome —
        that is the point). Returns a list of full token lists (prompt +
        generated) in submission order. The engine (paged KV pool +
        batched one-token decode, serving/engine.py) is cached on the
        model and reused across same-config calls; a different config
        replaces it (the old engine is shut down — each pins a device
        KV pool). Pass `engine=` to share one across models of the
        same weights, `serving_config=`/knobs to size it."""
        from ..serving import ServingEngine, ServingConfig
        eng = engine
        if eng is None:
            cfg = serving_config or ServingConfig(**engine_kw)
            # key on the resolved config's CONTENTS — two calls with
            # different knobs must not share an engine
            key = tuple(sorted((k, repr(v))
                               for k, v in vars(cfg).items()))
            eng = getattr(self, '_serving_engines', {}).get(key)
            if eng is None:
                # ONE live engine per model: each pins a full device KV
                # pool, so a config change evicts (and shuts down) the
                # old engine rather than growing an unbounded cache
                for old in getattr(self, '_serving_engines',
                                   {}).values():
                    old.shutdown()
                eng = ServingEngine(self, cfg)
                self._serving_engines = {key: eng}
        return eng.generate(prompts, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id,
                            temperature=temperature, top_k=top_k)

    def generate_scan(self, input_ids, max_new_tokens=32, temperature=1.0,
                      top_k=0, seed=0):
        """Whole-generation-in-one-dispatch decode: prefill + the full
        token loop run as ONE jitted lax.scan (amortizes host→device
        latency; on a tunneled chip this is the difference between
        ~140 ms/token and one RTT total). Sampling runs on device via
        jax.random; greedy when top_k == 0."""
        import numpy as np_
        from ..core.autograd import no_grad
        from ..jit import bind_arrays
        from jax import lax
        ids = np_.asarray(input_ids.data if isinstance(input_ids, Tensor)
                          else input_ids).astype('int32')
        B, L0 = ids.shape
        max_len = L0 + max_new_tokens
        if max_len > self.config.max_seq_len:
            raise ValueError(
                f"prompt({L0}) + max_new_tokens({max_new_tokens}) exceeds "
                f"max_seq_len({self.config.max_seq_len})")
        model = self
        params = {n: p.data for n, p in self.named_parameters()}
        was_training = self.training
        self.eval()

        def run(ps, prompt, key):
            caches = model.gpt.init_caches(B, max_len)
            kv0 = [(c[0].data, c[1].data) for c in caches]

            def one(tok, pos, kv):
                cts = [(Tensor(k), Tensor(v)) for k, v in kv]
                with bind_arrays(model, ps):
                    pos_ids = Tensor(pos[None].astype(jnp.int32))
                    h, ncs = model.gpt(Tensor(tok), pos_ids, caches=cts,
                                       cache_len=pos)
                    w = model.gpt.embeddings.word_embeddings.weight
                    logits = M.matmul(h, w, transpose_y=True)
                return logits.data[:, -1, :], [(c[0].data, c[1].data)
                                               for c in ncs]

            def prefill_step(kv, t):
                logits, kv = one(lax.dynamic_slice_in_dim(prompt, t, 1, 1),
                                 t, kv)
                return kv, logits

            kv, all_logits = lax.scan(prefill_step, kv0, jnp.arange(L0))
            last = all_logits[-1]

            def decode_step(carry, i):
                kv, last, k = carry
                scaled = last / jnp.maximum(temperature, 1e-6)
                if top_k and top_k > 0:
                    kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                    scaled = jnp.where(scaled < kth, -1e30, scaled)
                    k, sub = jax.random.split(k)
                    nxt = jax.random.categorical(sub, scaled, axis=-1)
                else:
                    nxt = jnp.argmax(scaled, axis=-1)
                nxt = nxt.astype(jnp.int32)
                last, kv = one(nxt[:, None], L0 + i, kv)
                return (kv, last, k), nxt

            (_, _, _), toks = lax.scan(
                decode_step, (kv, last, key), jnp.arange(max_new_tokens))
            return toks.T  # [B, max_new_tokens]

        with no_grad():
            key = jax.random.key(seed)
            cache_key = (B, L0, max_new_tokens, float(temperature),
                         int(top_k))
            if not hasattr(self, '_gen_cache'):
                self._gen_cache = {}
            jfn = self._gen_cache.get(cache_key)
            if jfn is None:
                jfn = jax.jit(run)
                self._gen_cache[cache_key] = jfn
            new = jfn(params, jnp.asarray(ids), key)
        if was_training:
            self.train()
        return Tensor(np_.concatenate([ids, np_.asarray(new)], axis=1))

    def _generate_cached(self, input_ids, max_new_tokens, temperature,
                         top_k, eos_token_id):
        import numpy as np_
        from ..core.autograd import no_grad
        from ..jit import bind_arrays
        ids = np_.asarray(input_ids.data if isinstance(input_ids, Tensor)
                          else input_ids).astype('int32')
        B, L0 = ids.shape
        max_len = min(self.config.max_seq_len, L0 + max_new_tokens)
        model = self
        params = {n: p.data for n, p in self.named_parameters()}
        was_training = self.training
        self.eval()  # generation is deterministic-forward; dropout keys
        # would otherwise bake into the trace as constants

        with no_grad():
            caches = self.gpt.init_caches(B, max_len)
            cache_arrays = [(c[0].data, c[1].data) for c in caches]

            def step(ps, token, pos, kv):
                cts = [(Tensor(k), Tensor(v)) for k, v in kv]
                with bind_arrays(model, ps):
                    pos_ids = Tensor(pos[None].astype(jnp.int32))
                    h, new_caches = model.gpt(Tensor(token), pos_ids,
                                              caches=cts, cache_len=pos)
                    w = model.gpt.embeddings.word_embeddings.weight
                    logits = M.matmul(h, w, transpose_y=True)
                new_kv = [(c[0].data, c[1].data) for c in new_caches]
                return logits.data[:, -1, :], new_kv

            # donate the cache so XLA updates it in place (no per-token
            # full-cache copy); cache the compiled step across calls
            if not hasattr(self, '_step_cache'):
                self._step_cache = {}
            ck = (B, max_len)
            jit_step = self._step_cache.get(ck)
            if jit_step is None:
                jit_step = jax.jit(step, donate_argnums=(3,))
                self._step_cache[ck] = jit_step

            # prefill: feed prompt tokens sequentially through the cache
            last_logits = None
            for t in range(L0):
                last_logits, cache_arrays = jit_step(
                    params, ids[:, t:t + 1], jnp.asarray(t, jnp.int32),
                    cache_arrays)

            out = ids
            # per-row EOS bookkeeping: stop as soon as every row has
            # emitted its EOS (not only when all rows emit it on the
            # SAME step) — emitted tokens stay identical, the loop just
            # skips the steps where everyone was already finished
            done = np_.zeros(B, bool)
            for i in range(max_new_tokens):
                pos = L0 + i
                if pos >= max_len:
                    break
                nxt = self._sample_next(np_.asarray(last_logits),
                                        temperature, top_k)
                out = np_.concatenate([out, nxt[:, None].astype('int32')],
                                      axis=1)
                if eos_token_id is not None:
                    done |= (nxt == eos_token_id)
                    if done.all():
                        break
                last_logits, cache_arrays = jit_step(
                    params, out[:, -1:], jnp.asarray(pos, jnp.int32),
                    cache_arrays)
        if was_training:
            self.train()
        return Tensor(out)


class GPTPretrainingCriterion(nn.Layer):
    """Parity: vocab-parallel softmax CE loss with mean over tokens."""

    def __init__(self, config=None):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels, loss_mask=None):
        loss = self.ce(logits, labels)
        if loss_mask is not None:
            masked = M.multiply(manip.reshape(loss, labels.shape), loss_mask)
            return M.divide(M.sum(masked), M.sum(loss_mask))
        return M.mean(loss)


class GPTLMHead(nn.Layer):
    """Final norm + (vocab-parallel) LM head + criterion — the last pipeline
    stage's tail. Untied head weight (the tied variant runs under the
    non-pipelined hybrid engine; tying across stages costs a pp-psum the
    engine applies to the embed tree — A.4)."""

    def __init__(self, config):
        super().__init__()
        self.norm = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_eps)
        init = I.Normal(0.0, config.initializer_range)
        self.out = ColumnParallelLinear(
            config.hidden_size, config.vocab_size,
            weight_attr=nn.ParamAttr(initializer=init),
            has_bias=False, gather_output=False)
        self.ce = ParallelCrossEntropy()

    def forward(self, hidden, labels):
        h = self.norm(hidden)
        from ..distributed import collective as C
        vocab_parallel = self.out.world_size > 1 and C.in_spmd_region()
        n_tokens = int(np.prod(h.shape[:-1]))
        if not vocab_parallel and n_tokens * self.out.out_features > 2 ** 28:
            # big-logits regime: chunked fused projection+xent — the
            # [tokens, vocab] logits never hit HBM (recompute backward, see
            # ops/nn_ops.fused_linear_cross_entropy). Below the threshold
            # the single matmul + fused hard-xent (bf16-only residual) is
            # faster: recompute would spend ~2% extra FLOPs to save memory
            # that isn't scarce.
            return F.fused_linear_cross_entropy(
                h, self.out.weight, labels, ignore_index=-100,
                transpose_y=False)
        logits = self.out(h)
        loss = self.ce(logits, labels)
        return M.mean(loss)


def build_gpt_pipeline(config):
    """(embed, blocks, head) triple for SpmdPipelineEngine."""
    embed = GPTEmbeddings(config)
    blocks = [GPTDecoderLayer(config) for _ in range(config.num_layers)]
    head = GPTLMHead(config)
    return embed, blocks, head


def gpt_pipeline_descs(config):
    """LayerDesc list for PipelineLayer partitioning (parity: pp GPT built
    from LayerDesc/SharedLayerDesc, pp_layers.py)."""
    from ..distributed.fleet.meta_parallel import LayerDesc, SharedLayerDesc
    descs = [SharedLayerDesc('embed', GPTEmbeddings, config=config)]
    for _ in range(config.num_layers):
        descs.append(LayerDesc(GPTDecoderLayer, config))
    descs.append(LayerDesc(nn.LayerNorm, config.hidden_size))
    return descs
