"""Flagship model zoo (language models; vision lives in paddle_tpu.vision).

Reference parity: the GPT/BERT model definitions used by the reference's
fleet hybrid-parallel tests (hybrid_parallel_pp_transformer.py,
hybrid_parallel_mp_model.py patterns) and the PaddleNLP GPT that
sandyhouse/Paddle's pipeline/sharding work was built to train.
"""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM,
                  GPTPretrainingCriterion, gpt_tiny, gpt_small, gpt_medium,
                  gpt_1p3b)
from .bert import BertConfig, BertModel, BertForPretraining
from .deepfm import DeepFM, deepfm_loss  # noqa: F401,E402
