"""Framework-level utilities: save/load, default dtype, places, paddle.grad.

Reference parity: python/paddle/framework/io.py (save:550/load:766 — pickled
nested state dicts of numpy arrays, protocol 4), framework.py places, and
imperative/partial_grad_engine.cc for `paddle.grad`.
"""
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .core import dtypes as _dtypes
from .core import autograd as _autograd
from .core.tensor import Tensor

_default_dtype = jnp.float32


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = _dtypes.convert_dtype(d)


def get_default_dtype():
    return _dtypes.dtype_name(_default_dtype)


def in_dynamic_mode():
    return True


def set_grad_enabled(mode):
    class _Guard:
        def __enter__(self):
            self._saved = _autograd._grad_enabled
            _autograd._grad_enabled = bool(mode)
            return self
        def __exit__(self, *a):
            _autograd._grad_enabled = self._saved
            return False
    return _Guard()


def is_grad_enabled():
    return _autograd.grad_enabled()


# ---- places -----------------------------------------------------------------
class Place:
    def __init__(self, idx=0):
        self.idx = idx

    def __repr__(self):
        return f"{type(self).__name__}({self.idx})"


class CPUPlace(Place):
    pass


class CUDAPlace(Place):  # accepted for API compat; maps to the TPU device
    pass


class CUDAPinnedPlace(Place):
    pass


class TPUPlace(Place):
    """The native device of this framework (parity: platform/device_context.h
    Place variants — here PJRT owns the device)."""


_current_device = 'tpu'


def set_device(device):
    global _current_device
    _current_device = device
    return device


def get_device():
    return _current_device


# ---- save / load ------------------------------------------------------------
def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(jax.device_get(obj.data))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Parity: paddle.save (framework/io.py:550) — pickled numpy state dicts."""
    with open(path, 'wb') as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


class _SafeUnpickler(pickle.Unpickler):
    """paddle.load keeps the reference's pickled-state-dict format
    (framework/io.py:766) but refuses to resolve any global outside a
    numpy/stdlib-container whitelist, so a crafted checkpoint cannot
    execute arbitrary code on load."""

    _ALLOWED = {
        ('collections', 'OrderedDict'),
        ('numpy', 'ndarray'), ('numpy', 'dtype'),
        ('numpy.core.multiarray', '_reconstruct'),
        ('numpy._core.multiarray', '_reconstruct'),
        ('numpy.core.multiarray', 'scalar'),
        ('numpy._core.multiarray', 'scalar'),
    }

    def find_class(self, module, name):
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"paddle.load: refusing to unpickle global {module}.{name} "
            "(only numpy arrays / containers are allowed in checkpoints)")


def load(path, **configs):
    """Parity: paddle.load (framework/io.py:766). Unpickling is
    restricted to numpy/stdlib containers — see _SafeUnpickler."""
    with open(path, 'rb') as f:
        obj = _SafeUnpickler(f).load()

    def back(o):
        if isinstance(o, np.ndarray):
            return Tensor(o)
        if isinstance(o, dict):
            return {k: back(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return type(o)(back(v) for v in o)
        return o
    if configs.get('return_numpy', False):
        return obj
    return back(obj)


# ---- paddle.grad -------------------------------------------------------------
def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Parity: paddle.grad → PartialGradEngine (partial_grad_engine.cc).

    Computes d(outputs)/d(inputs) without touching `.grad` of other leaves.
    Implemented by running the tape backward into a scratch grad map.
    """
    single = isinstance(inputs, Tensor)
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if single:
        inputs = [inputs]
    capture = {id(t): None for t in inputs}
    _autograd.backward(outputs, grad_outputs,
                       retain_graph=True if retain_graph is None else retain_graph,
                       capture=capture, create_graph=create_graph)
    grads = []
    for i, t in enumerate(inputs):
        g = capture[id(t)]
        if g is None and not allow_unused:
            raise RuntimeError(
                f"paddle.grad: input {i} is unreachable from outputs "
                "(no grad path); pass allow_unused=True to get None "
                "instead")
        if g is None:
            grads.append(None)
        elif isinstance(g, Tensor):
            # create_graph: keep the live tape so grads are differentiable
            grads.append(g)
        else:
            grads.append(Tensor(g, stop_gradient=True))
    return grads
