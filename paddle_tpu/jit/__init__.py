"""paddle_tpu.jit — dygraph→static bridge and jitted train steps.

Reference parity: python/paddle/jit (to_static / TranslatedLayer) and
dygraph_to_static/program_translator.py. TPU-native design: instead of
AST-rewriting Python into a ProgramDesc, the eager Layer IS the trace — we run
it under `jax.jit` with its parameters/buffers lifted to function inputs
(functional_call), so the whole step compiles to ONE XLA executable. That is
the idiomatic XLA replacement for the reference's per-op executor hot loop
(operator.cc:1075 RunImpl) and delivers the fusion/latency win the op-function
codegen (pybind/op_function_generator.cc) chases on GPU.

`TrainStep` compiles forward+backward+optimizer into a single program with
donated buffers (grads via jax.grad at trace level — the tape is bypassed).
"""
import contextlib
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core import rng as rng_mod
from ..core import autograd
from ..core.tensor import Tensor


def _named_params(layer):
    return list(layer.named_parameters())


def _named_buffers(layer):
    return [(n, b) for n, b in layer.named_buffers() if b is not None]


@contextlib.contextmanager
def bind_arrays(layer, param_arrays, buffer_arrays=None):
    """Temporarily swap layer parameter/buffer .data with given arrays
    (tracers under jit). Yields a dict to collect mutated buffer values."""
    params = _named_params(layer)
    buffers = _named_buffers(layer)
    saved_p = [(p, p._data) for _, p in params]
    saved_b = [(b, b._data) for _, b in buffers]
    try:
        for (n, p) in params:
            p._data = param_arrays[n]
        if buffer_arrays is not None:
            for (n, b) in buffers:
                if n in buffer_arrays:
                    b._data = buffer_arrays[n]
        out_buffers = {}
        yield out_buffers
        for (n, b) in buffers:
            out_buffers[n] = b._data
    finally:
        for p, d in saved_p:
            p._data = d
        for b, d in saved_b:
            b._data = d


def functional_call(layer, param_arrays, args, buffer_arrays=None,
                    rng_key=None):
    """Run `layer(*args)` with parameters bound from `param_arrays`.

    Returns (output arrays pytree, new_buffer_arrays). Pure if the layer is —
    the substrate for jit/pjit'd steps.
    """
    with bind_arrays(layer, param_arrays, buffer_arrays) as out_buffers:
        ctx = rng_mod.rng_guard(rng_key) if rng_key is not None \
            else contextlib.nullcontext()
        with ctx, autograd.no_grad():
            out = layer(*[Tensor(a) if not isinstance(a, Tensor) else a
                          for a in args])
        out_arrays = jax.tree_util.tree_map(
            lambda t: t.data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))
    return out_arrays, dict(out_buffers)


def get_params(layer):
    """Extract {name: array} of trainable parameters."""
    return {n: p.data for n, p in _named_params(layer)
            if not p.stop_gradient}


def get_buffers(layer):
    return {n: b.data for n, b in _named_buffers(layer)}


def write_back(layer, param_arrays=None, buffer_arrays=None):
    if param_arrays:
        lookup = dict(_named_params(layer))
        for n, arr in param_arrays.items():
            lookup[n]._data = arr
    if buffer_arrays:
        lookup = dict(_named_buffers(layer))
        for n, arr in buffer_arrays.items():
            if n in lookup:
                lookup[n]._data = arr


from ..core.async_step import AsyncDispatchMixin as _AsyncDispatchMixin


class TrainStep(_AsyncDispatchMixin):
    """One fully-jitted train step: forward, backward, clip, optimizer.

    loss_fn(model, *batch_tensors) -> scalar loss Tensor.
    """

    def __init__(self, model, loss_fn, optimizer, donate=True,
                 use_buckets=None, comm_overlap=None, prefetch_depth=None,
                 comm_chunk=None, remat_policy=None, dispatch_window=None,
                 device_lr=None):
        from ..core import async_step as A_
        from ..core import bucketing as B
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # tuned remat (docs/performance.md#remat-policy): kwarg ->
        # PTPU_REMAT_POLICY -> strategy.recompute_configs['policy'];
        # the single-program step historically ran without remat, so the
        # default stays 'none'
        from ..distributed.fleet.utils.recompute import (
            resolve_policy as _resolve_remat)
        self._remat_policy = _resolve_remat(remat_policy,
                                                       default='none')
        self._param_names = [n for n, p in _named_params(model)
                             if not p.stop_gradient]
        # copies, not views: the compiled step DONATES these buffers and the
        # eager layer must keep its own arrays alive for eval/save
        self._params = {n: jnp.array(a, copy=True)
                        for n, a in get_params(model).items()}
        self._buffers = {n: jnp.array(a, copy=True)
                         for n, a in get_buffers(model).items()}
        lookup = dict(_named_params(model))
        # bucketed optimizer phase (core/bucketing.py): elementwise
        # optimizers update a handful of flat dtype-homogeneous buckets
        # instead of one kernel chain per parameter — same math (the
        # update is per-element), fewer/larger fused kernels
        self._use_buckets = (use_buckets is not False
                             and B.elementwise(optimizer)
                             and bool(self._param_names))
        # comm-overlap knobs are accepted for engine-API uniformity and
        # recorded in the gauges, but the single-program path has NO
        # collectives to overlap (n_shards=1) — grouping stays off so
        # the compiled program is unchanged with the knob on (the
        # ISSUE-10 dp=1 acceptance invariant)
        self._comm_overlap, self._prefetch_depth, self._comm_chunk = \
            B.resolve_overlap_config(comm_overlap, prefetch_depth,
                                     comm_chunk)
        if self._use_buckets:
            _, bucket_bytes = B.resolve_comm_config()
            self._layout = B.BucketLayout.build(
                {n: (lookup[n].data.shape, lookup[n].data.dtype)
                 for n in self._param_names},
                bucket_bytes=bucket_bytes, pad_to=8)
            self._opt_states = []
            for b in self._layout.buckets:
                flat32 = np.zeros((b.size,), np.float32)
                for s in b.slots:
                    flat32[s.offset:s.offset + s.size] = np.asarray(
                        jax.device_get(lookup[s.name].data),
                        np.float32).reshape(-1)
                st = B.init_bucket_state(optimizer, b, flat32)
                self._opt_states.append(
                    {k: jnp.asarray(v) for k, v in st.items()})
            B.publish_comm_gauges(self._layout, engine='jit', n_shards=1,
                                  enabled=False)
            B.publish_overlap_gauges(self._layout, engine='jit',
                                     n_shards=1, enabled=False,
                                     prefetch=self._prefetch_depth,
                                     chunk=self._comm_chunk)
        else:
            self._layout = None
            self._opt_states = {}
            for n in self._param_names:
                st = optimizer.init_state(lookup[n])
                if lookup[n].data.dtype != jnp.float32 and \
                        getattr(optimizer, '_multi_precision', True):
                    # pre-seed the fp32 master so the state pytree
                    # structure is stable across steps (lax.scan carry
                    # requirement)
                    st['master'] = lookup[n].data.astype(jnp.float32)
                self._opt_states[n] = st
        # numerics taps (core/numerics.py): latched here — they change
        # the compiled step's output tree, so set FLAGS before building
        from ..core import numerics as _num
        self._taps_on = _num.taps_enabled()
        # -- async step pipeline (ISSUE 13,
        # docs/performance.md#async-dispatch): bounded in-flight window,
        # host-gap instrumentation, on-device LR schedule ----------------
        self._inflight = A_.DispatchWindow(
            A_.resolve_dispatch_window(dispatch_window))
        self._gap = A_.HostGapMonitor('jit')
        # step-time ledger (ISSUE 16): wall decomposition + model-FLOPs
        # accounting, published from flush()
        from ..core import ledger as _led
        self._ledger = _led.StepLedger(
            'jit', gap=self._gap,
            params_fn=lambda: _led.count_params(self._params),
            remat_policy=self._remat_policy)
        from ..optimizer import device_lr as _dlr
        self._lr = _dlr.LrFeed(optimizer, device_lr)
        self._compiled = jax.jit(
            self._step,
            donate_argnums=(0, 1, 2) if donate else ())
        self._exec_cache = {}    # batch signature -> AOT executable
        self._step_i = 0

    def _step(self, params, buffers, opt_states, lr, key, batch):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        # on-device LR schedule: `lr` carries the device int32 step
        # counter; the traced schedule derives this step's lr and the
        # incremented counter rides out as an extra output
        step_c = None
        if self._lr.fn is not None:
            step_c = lr
            lr = self._lr.fn(step_c).astype(jnp.float32)

        def loss_of(ps, bufs):
            with bind_arrays(model, ps, bufs) as out_bufs:
                with rng_mod.rng_guard(key), autograd.no_grad():
                    loss = loss_fn(model, *[Tensor(b) for b in batch])
            return loss.data.astype(jnp.float32), dict(out_bufs)

        from ..distributed.fleet.utils.recompute import (
            apply_policy as _apply_remat)
        loss_of = _apply_remat(loss_of, self._remat_policy,
                                          engine='jit')
        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params, buffers)
        if self._use_buckets:
            from ..core import bucketing as B
            new_params, new_states = B.flat_functional_apply(
                opt, self._layout, params, grads, opt_states, lr)
        else:
            new_params, new_states = opt.functional_apply(params, grads,
                                                          opt_states, lr)
        out = (loss, new_params, new_buffers, new_states)
        if step_c is not None:
            out = out + (step_c + 1,)
        if self._taps_on:
            from ..core import numerics as _num
            taps = _num.jit_taps(grads, new_params)
            return out + (taps,)
        return out

    def _dispatch(self, batch):
        from .. import profiler as _prof
        from ..core import async_step as A_
        from ..core.monitor import stat_add
        # gap bracket opens BEFORE any jax client call (asarray/key
        # fold-in can serialize behind in-flight compute — dispatch
        # time, not inter-dispatch host gap)
        self._gap.dispatch_begin()
        arrays = tuple(b.data if isinstance(b, Tensor) else jnp.asarray(b)
                       for b in batch)
        if arrays:
            self._ledger.observe_batch(arrays[0].shape)
        key = rng_mod.next_key()
        args = (self._params, self._buffers, self._opt_states,
                self._lr.arg(), key, arrays)
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        exe = self._exec_cache.get(sig)
        if exe is None:
            # compile split out from the steady-state step (observability
            # v2): lower/compile spans + compile-seconds/FLOP metrics
            stat_add('STAT_trainstep_compiles')
            with _prof.RecordEvent('jit::train_step_compile',
                                   event_type='compile'):
                exe, _ = _prof.compile_with_telemetry(
                    self._compiled, 'train_step', args)
            self._exec_cache[sig] = exe
        with _prof.RecordEvent('jit::train_step', event_type='jit'):
            try:
                out = exe(*args)
            except TypeError:
                # AOT signature drift (e.g. dtype-only change): retrace
                if exe is self._compiled:
                    raise
                self._exec_cache[sig] = self._compiled
                out = self._compiled(*args)
        self._gap.dispatch_end(depth=len(self._inflight) + 1)
        loss, self._params, self._buffers, self._opt_states = out[:4]
        i = 4
        if self._lr.fn is not None:
            self._lr.carry = out[i]
            i += 1
        taps = out[i] if self._taps_on else None
        step_no = self._step_i
        self._step_i += 1
        on_drain = None
        if taps is not None:
            def on_drain(res, _t=taps, _s=step_no):
                from ..core import numerics as _num
                meta = {k: {n: (a.shape, a.dtype)
                            for n, a in self._params.items()}
                        for k in ('grads', 'params')}
                self.last_numerics = _num.process_jit_taps(
                    _t, site='jit', step=_s, meta=meta)
        return A_.AsyncResult(loss, step_no, taps=taps,
                              on_drain=on_drain, monitor=self._gap)

    def __call__(self, *batch):
        if len(self._inflight):
            # mixed APIs: drain queued async steps FIRST so deferred
            # work (taps processing) keeps submission order
            self.flush()
        res = self._dispatch(batch)
        res.wait()     # legacy per-step semantics: taps processed now
        return Tensor(res.loss)

    def train_step(self, *batch):
        """Async dispatch (docs/performance.md#async-dispatch): returns
        an AsyncResult; the bounded in-flight window
        (PTPU_DISPATCH_WINDOW) drains the oldest step as it fills."""
        return self._inflight.push(self._dispatch(batch))

    def input_sharding(self, index, ndim):
        """DeviceLoader contract: single-program step — batches go to
        the default device whole."""
        return None

    def sync_model(self):
        """Write jitted state back into the eager Layer (for save/eval).
        Drains the async dispatch window first."""
        self.flush()
        write_back(self.model, self._params, self._buffers)

    # -- multi-step: k steps per dispatch (amortizes host→device launch; on
    # a tunneled/remote chip this is the difference between RTT-bound and
    # compute-bound) ---------------------------------------------------------
    def compile_multi_step(self, k=None):
        if getattr(self, '_multi', None) is not None:
            return  # jax.jit caches per input shape — one jit covers all k
        step = self._step
        device_lr = self._lr.fn is not None

        def many(params, buffers, opt_states, lr, keys, batch_stack):
            def body(carry, xs):
                p, b, s, c = carry
                key = xs[0]
                batch = xs[1]
                # trailing outputs (numerics taps) don't escape a
                # scanned multi-step; XLA DCEs them. Under on-device LR
                # the step counter advances through the scan carry.
                out = step(p, b, s, c, key, batch)
                c2 = out[4] if device_lr else c
                return (out[1], out[2], out[3], c2), out[0]
            (p, b, s, c), losses = jax.lax.scan(
                body, (params, buffers, opt_states, lr),
                (keys, batch_stack))
            return losses, p, b, s, c

        self._multi = jax.jit(many, donate_argnums=(0, 1, 2))

    def run_steps(self, *batch_stacks):
        """Each arg: array with leading dim k (one slice per step). Returns
        the k per-step losses as one Tensor."""
        if len(self._inflight):
            # mixed APIs: drain queued async steps FIRST so deferred
            # work keeps submission order (same rule as __call__)
            self.flush()
        arrays = tuple(b.data if isinstance(b, Tensor) else jnp.asarray(b)
                       for b in batch_stacks)
        k = arrays[0].shape[0]
        self.compile_multi_step()
        lr = self._lr.arg()
        keys = jax.random.split(rng_mod.next_key(), k)
        (losses, self._params, self._buffers, self._opt_states,
         lr_out) = self._multi(
            self._params, self._buffers, self._opt_states, lr, keys,
            arrays)
        if self._lr.fn is not None:
            self._lr.carry = lr_out
        self._step_i += k
        return Tensor(losses)


class EvalStep:
    """Jitted forward pass for inference."""

    def __init__(self, model):
        self.model = model
        self._compiled = jax.jit(self._fwd)

    def _fwd(self, params, buffers, batch):
        out, _ = functional_call(self.model, params, batch, buffers)
        return out

    def __call__(self, *batch):
        arrays = tuple(b.data if isinstance(b, Tensor) else jnp.asarray(b)
                       for b in batch)
        params = {n: p.data for n, p in _named_params(self.model)}
        out = self._compiled(params, get_buffers(self.model), arrays)
        return jax.tree_util.tree_map(Tensor, out)


class StaticFunction:
    """Parity: dygraph_to_static StaticFunction:232 — wraps a function or a
    Layer method; each distinct input signature compiles once into a cached
    XLA executable (the ProgramCache:692 analogue is jax.jit's cache)."""

    def __init__(self, function, input_spec=None):
        # dy2static: rewrite data-dependent if/while/for-range into
        # lax.cond/while_loop dispatchers before tracing (parity:
        # program_translator's AST conversion)
        from . import dy2static
        self._function = dy2static.convert_function(function)
        self._dygraph_function = function
        self._layer = getattr(function, '__self__', None)
        self.input_spec = input_spec
        self._jit_cache = {}   # static-kwargs snapshot -> jitted trace
        self._exec_cache = {}  # (skey, shape sig) -> AOT executable
        self._compiled_sigs = set()

    def __call__(self, *args, **kwargs):
        if not ProgramTranslator.get_instance().enable_to_static:
            return self._dygraph_function(*args, **kwargs)
        # tensor kwargs trace as inputs; other kwargs are compile-time
        # constants keyed into the cache (a new value recompiles instead
        # of silently reusing the first call's)
        t_kwargs = {k: v for k, v in kwargs.items()
                    if isinstance(v, Tensor)}
        s_kwargs = {k: v for k, v in kwargs.items()
                    if not isinstance(v, Tensor)}
        # positional args: tensors/numerics trace; anything else is a
        # compile-time constant keyed into the cache
        spec, arrays, static_pos = [], [], {}
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                spec.append('t')
                arrays.append(a.data)
            elif isinstance(a, (np.ndarray, jnp.ndarray)):
                spec.append('t')
                arrays.append(jnp.asarray(a))
            else:   # python scalars/objects are compile-time constants
                spec.append('s')
                static_pos[i] = a

        def _hkey(items):
            try:
                k = tuple(items)
                hash(k)
                return k
            except TypeError:
                return tuple((a, repr(b)) for a, b in items)
        from .. import profiler as _prof
        from ..core.monitor import counter
        skey = (tuple(spec), _hkey(sorted(static_pos.items())),
                _hkey(sorted(s_kwargs.items())))
        jitted = self._jit_cache.get(skey)
        counter('ptpu_jit_cache_total',
                help='StaticFunction program-cache lookups',
                labelnames=('result',)).inc(
                    1, result='hit' if jitted is not None else 'miss')
        if jitted is None:
            fn = self._function
            layer = self._layer

            def traced(params, buffers, key, arrs, t_arrays,
                       _sk=dict(s_kwargs), _sp=dict(static_pos),
                       _spec=tuple(spec)):
                it = iter(arrs)
                full = [Tensor(next(it)) if s == 't' else _sp[i]
                        for i, s in enumerate(_spec)]
                with bind_arrays(layer, params, buffers) if layer is not None \
                        else contextlib.nullcontext() as _:
                    with rng_mod.rng_guard(key), autograd.no_grad():
                        kw = dict(_sk)
                        kw.update({k: Tensor(a)
                                   for k, a in t_arrays.items()})
                        out = fn(*full, **kw)
                return jax.tree_util.tree_map(
                    lambda t: t.data if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            jitted = self._jit_cache[skey] = jax.jit(traced)
        if self._layer is not None:
            params = {n: p.data for n, p in _named_params(self._layer)}
            buffers = get_buffers(self._layer)
        else:
            params, buffers = {}, {}
        call_args = (params, buffers, rng_mod.next_key(), tuple(arrays),
                     {k: v.data for k, v in t_kwargs.items()})
        # per-shape executable cache: jax.jit retraces internally on new
        # shapes; tracking it here splits trace/lower/compile into spans
        # and compile-seconds metrics (jax caches per aval signature)
        shape_sig = (skey, tuple(
            (tuple(getattr(l, 'shape', ())), str(getattr(l, 'dtype', '')))
            for l in jax.tree_util.tree_leaves(
                (params, buffers, call_args[3], call_args[4]))))
        if shape_sig not in self._compiled_sigs:
            self._compiled_sigs.add(shape_sig)
            with _prof.RecordEvent('dy2static::trace_compile',
                                   event_type='compile'):
                exe, ok = _prof.compile_with_telemetry(
                    jitted, 'dy2static', call_args)
            if ok:
                self._exec_cache[shape_sig] = exe
        exe = self._exec_cache.get(shape_sig, jitted)
        with _prof.RecordEvent('dy2static::call', event_type='jit'):
            try:
                out = exe(*call_args)
            except TypeError:
                if exe is jitted:
                    raise
                self._exec_cache.pop(shape_sig, None)
                out = jitted(*call_args)
        return jax.tree_util.tree_map(Tensor, out)


def to_static(function=None, input_spec=None, build_strategy=None,
              property=False):
    """Parity: paddle.jit.to_static decorator."""
    def decorate(fn):
        if isinstance(fn, type):
            raise TypeError("to_static expects a function or Layer instance")
        from ..nn.layer.base import Layer
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec)
            fn.forward = sf
            return fn
        return functools.wraps(fn)(StaticFunction(fn, input_spec))
    if function is not None:
        return decorate(function)
    return decorate


def save(layer, path, input_spec=None, **configs):
    """Parity: paddle.jit.save — persists state dict (program export lands
    with paddle_tpu.static serialization)."""
    from .. import framework
    framework.save(layer.state_dict(), path + '.pdparams')


def load(path, **configs):
    from .. import framework
    return framework.load(path + '.pdparams')


def not_to_static(fn):
    return fn


class ProgramTranslator:
    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self.enable_to_static = True

    def enable(self, enable_to_static):
        self.enable_to_static = enable_to_static


def enable_to_static(flag=True):
    ProgramTranslator.get_instance().enable(flag)


# -- dy2static logging + traced-layer sheet ---------------------------------

_verbosity = 0
_code_level = 0


def set_verbosity(level=0, also_to_stdout=False):
    """paddle.jit.set_verbosity — dy2static transform logging level."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """paddle.jit.set_code_level — print transformed code at/below the
    given level."""
    global _code_level
    _code_level = int(level)


class TranslatedLayer:
    """paddle.jit.TranslatedLayer — the callable a jit.load returns
    (wraps a loaded inference Program + params; parity:
    fluid/dygraph/io.py TranslatedLayer)."""

    def __init__(self, program, feed_names, fetch_vars, scope=None):
        self._program = program
        self._feed_names = feed_names
        self._fetch = fetch_vars
        self._scope = scope

    def __call__(self, *args):
        from ..static.executor import Executor
        exe = Executor()
        feed = {n: (a.data if isinstance(a, Tensor) else a)
                for n, a in zip(self._feed_names, args)}
        outs = exe.run(self._program, feed=feed, fetch_list=self._fetch)
        outs = [Tensor(jnp.asarray(o)) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def eval(self):
        return self

    def train(self):
        raise NotImplementedError(
            "TranslatedLayer wraps an inference program; rebuild the "
            "dygraph Layer for training")


class TracedLayer:
    """paddle.jit.TracedLayer — trace a dygraph layer into a static
    program via to_static machinery (fluid/dygraph/jit.py). `trace`
    returns (outputs, traced) where traced(input...) replays the
    compiled function."""

    def __init__(self, fn, example_args):
        self._fn = fn
        self._compiled = jax.jit(fn)
        self._example = example_args

    @staticmethod
    def trace(layer, inputs):
        inputs = list(inputs)

        def fn(*arrs):
            outs = layer(*[Tensor(a) for a in arrs])
            if isinstance(outs, (list, tuple)):
                return [o.data for o in outs]
            return outs.data
        arrs = [i.data if isinstance(i, Tensor) else jnp.asarray(i)
                for i in inputs]
        traced = TracedLayer(fn, arrs)
        out = traced(*inputs)
        return out, traced

    def __call__(self, *args):
        arrs = [a.data if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out = self._compiled(*arrs)
        if isinstance(out, (list, tuple)):
            outs = [Tensor(o) for o in out]
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)

    def save_inference_model(self, path, feed=None, fetch=None):
        raise NotImplementedError(
            "TracedLayer.save_inference_model: use paddle.jit.save / "
            "static.save_inference_model (StableHLO export) instead")
