"""dy2static: AST conversion of data-dependent Python control flow.

Reference parity: dygraph_to_static/program_translator.py:232-759 and the
per-construct transformers (ifelse_transformer, loop_transformer,
logical_transformer): `@to_static` functions get their source rewritten so
`if`/`while`/`for range()` over tensors become runtime conversion calls.

TPU-native lowering: the reference converts to conditional_block/while ops
in a ProgramDesc; here the runtime calls dispatch on whether the condition
is a traced value — `lax.cond` / `lax.while_loop` under jit (XLA-native
control flow, SURVEY N28), plain Python control flow otherwise. State is
threaded functionally: the transformer hoists each branch/body into a
closure that mutates enclosing locals via `nonlocal`, plus get/set closures
over the union of assigned names — exactly the reference's
get_args/set_args convention (convert_operators.py convert_ifelse /
convert_while_loop).

Conversion is conservative where it must be: an `if` whose subtree
contains return inside a loop is left as Python control flow (fine for
Python conditions; tensor conditions there raise jax's tracer error).
break/continue lower to flag variables with guarded fall-through
(break_continue_transformer.py parity), and every call site dispatches
through convert_call so callee functions convert recursively
(convert_call_func.py parity). Converted code executes against the
function's LIVE globals — later rebinding of module names behaves
exactly as in eager.
"""
import ast
import functools
import inspect
import textwrap

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor


class _UndefinedType:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return '<undefined>'


UNDEFINED = _UndefinedType()


def _raw(x):
    return x.data if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_raw(x), jax.core.Tracer)


def to_bool(x):
    r = _raw(x)
    if isinstance(r, jax.core.Tracer):
        return r.reshape(()).astype(bool)
    return bool(np.asarray(r).reshape(()))


# ---- state packing ----------------------------------------------------------
def _flatten_state(state):
    leaves, treedef = jax.tree_util.tree_flatten(
        state, is_leaf=lambda t: isinstance(t, Tensor))
    kinds, carry, statics = [], [], []
    for lf in leaves:
        if isinstance(lf, Tensor):
            kinds.append('t')
            carry.append(lf.data)
        elif isinstance(lf, (jax.Array, jax.core.Tracer)):
            kinds.append('a')
            carry.append(lf)
        elif isinstance(lf, (bool, int, float, np.generic)) \
                and not isinstance(lf, _UndefinedType):
            kinds.append('a')   # python numbers ride the carry as arrays
            carry.append(jnp.asarray(lf))
        else:
            kinds.append('s')
            statics.append(lf)
    return treedef, kinds, carry, statics


def _unflatten_state(treedef, kinds, carry, statics):
    leaves, ci, si = [], 0, 0
    for k in kinds:
        if k == 't':
            leaves.append(Tensor(carry[ci]))
            ci += 1
        elif k in ('a', 'n'):
            leaves.append(carry[ci])
            ci += 1
        else:
            leaves.append(statics[si])
            si += 1
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _check_match(tag, treedef, kinds, treedef2, kinds2):
    if treedef != treedef2 or kinds != kinds2:
        raise TypeError(
            f"dy2static {tag}: control-flow state diverged between paths "
            "(a variable is defined/typed in only one branch, or changes "
            "its structure inside the loop) — give it a value of the same "
            "type on every path before the control flow")


def _check_statics(tag, statics, statics2):
    for a, b in zip(statics, statics2):
        if a is b:
            continue
        try:
            if a == b:
                continue
        except Exception:
            pass
        raise TypeError(
            f"dy2static {tag}: a non-tensor value ({a!r} vs {b!r}) is "
            "assigned differently under a traced condition — make it a "
            "tensor, or lift the assignment out of the converted branch")


# ---- static-Program recording branch ---------------------------------------
def _flatten_static_state(state):
    """Like _flatten_state but for static Variables (Program recording):
    Variables ride the carry; everything else is static."""
    from ..static.program import Variable as SV
    leaves, treedef = jax.tree_util.tree_flatten(
        state, is_leaf=lambda t: isinstance(t, (Tensor, SV)))
    kinds, carry, statics = [], [], []
    for lf in leaves:
        if isinstance(lf, (SV, Tensor)):
            # concrete Tensors (e.g. paddle.zeros initials) ride the carry
            # too — the recorders materialize them as captured consts
            kinds.append('v')
            carry.append(lf)
        else:
            kinds.append('s')
            statics.append(lf)
    return treedef, kinds, carry, statics


def _unflatten_static_state(treedef, kinds, carry, statics):
    leaves, ci, si = [], 0, 0
    for k in kinds:
        if k == 'v':
            leaves.append(carry[ci])
            ci += 1
        else:
            leaves.append(statics[si])
            si += 1
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _static_ifelse(pred, true_fn, false_fn, get_state, set_state):
    """Record a conditional_block op with sub-blocks instead of tracing
    lax.cond — the Program carries the control flow (VERDICT r2 #3)."""
    from ..static import control_flow as CF
    init = get_state()
    td0, k0, c0, s0 = _flatten_static_state(init)

    def branch(fn):
        def run():
            set_state(_unflatten_static_state(td0, k0, list(c0), s0))
            fn()
            td2, k2, c2, s2 = _flatten_static_state(get_state())
            _check_match('if', td0, k0, td2, k2)
            return c2
        return run

    outs = CF._record_cond(pred, branch(true_fn), branch(false_fn))
    outs = [] if outs is None else (
        list(outs) if isinstance(outs, tuple) else [outs])
    set_state(_unflatten_static_state(td0, k0, outs, s0))


def _static_while(cond_fn, body_fn, get_state, set_state):
    from ..static import control_flow as CF
    init = get_state()
    td0, k0, c0, s0 = _flatten_static_state(init)

    def c(*carry):
        set_state(_unflatten_static_state(td0, k0, list(carry), s0))
        return cond_fn()

    def b(*carry):
        set_state(_unflatten_static_state(td0, k0, list(carry), s0))
        body_fn()
        td2, k2, c2, s2 = _flatten_static_state(get_state())
        _check_match('while', td0, k0, td2, k2)
        return c2

    outs = CF._record_while(c, b, c0)
    set_state(_unflatten_static_state(td0, k0, list(outs), s0))


def _static_pred(pred):
    from ..static.program import Variable as SV
    return isinstance(pred, SV)


def _state_is_static(state):
    from ..static.program import Variable as SV
    leaves, _ = jax.tree_util.tree_flatten(
        state, is_leaf=lambda t: isinstance(t, (Tensor, SV)))
    return any(isinstance(lf, SV) for lf in leaves)


# ---- runtime converters -----------------------------------------------------
def convert_ifelse(pred, true_fn, false_fn, get_state, set_state):
    """Parity: convert_operators.convert_ifelse — lax.cond when the
    predicate is traced, Python if otherwise."""
    if _static_pred(pred):
        return _static_ifelse(pred, true_fn, false_fn, get_state,
                              set_state)
    p = _raw(pred)
    if not isinstance(p, jax.core.Tracer):
        if bool(np.asarray(p).reshape(())):
            true_fn()
        else:
            false_fn()
        return
    init = get_state()
    treedef0, kinds0, carry0, statics0 = _flatten_state(init)
    out_spec = {}

    def run_branch(fn, carry):
        set_state(_unflatten_state(treedef0, kinds0, carry, statics0))
        fn()
        td2, k2, c2, s2 = _flatten_state(get_state())
        # branches' OUTPUT trees must match each other (not the input:
        # a var first assigned inside both branches is fine)
        if 'spec' not in out_spec:
            out_spec['spec'] = (td2, k2, s2)
        else:
            _check_match('if', out_spec['spec'][0], out_spec['spec'][1],
                         td2, k2)
            _check_statics('if', out_spec['spec'][2], s2)
        return c2

    out = lax.cond(p.reshape(()).astype(bool),
                   lambda c: run_branch(true_fn, c),
                   lambda c: run_branch(false_fn, c),
                   carry0)
    td2, k2, s2 = out_spec['spec']
    set_state(_unflatten_state(td2, k2, out, s2))


def _run_lax_while(cond_fn, body_fn, get_state, set_state):
    """lax.while_loop over positionally-planned carry: non-static leaves
    carry; static leaves must not change — EXCEPT leaves that start
    UNDEFINED, which are loop-LOCALS (assigned-before-use temporaries,
    the reference's loop-var liveness refinement): they are recomputed
    each iteration, never carried, and read back as UNDEFINED after the
    loop."""
    leaves0, treedef = jax.tree_util.tree_flatten(
        get_state(), is_leaf=lambda t: isinstance(t, Tensor))
    n = len(leaves0)

    def kind_of(lf):
        if isinstance(lf, Tensor):
            return 't'
        if isinstance(lf, (jax.Array, jax.core.Tracer)):
            return 'a'
        if isinstance(lf, (bool, int, float, np.generic)) \
                and not isinstance(lf, _UndefinedType):
            return 'a'
        return 's'

    kinds0 = [kind_of(lf) for lf in leaves0]
    carry_pos = [i for i in range(n) if kinds0[i] != 's']

    def to_state(carry):
        full = list(leaves0)
        for j, i in enumerate(carry_pos):
            full[i] = Tensor(carry[j]) if kinds0[i] == 't' else carry[j]
        return jax.tree_util.tree_unflatten(treedef, full)

    def extract_carry(leaves, tag):
        out = []
        for i in carry_pos:
            lf = leaves[i]
            k = kind_of(lf)
            if k != kinds0[i]:
                raise TypeError(
                    f"dy2static {tag}: control-flow state changed kind "
                    f"inside the loop ({kinds0[i]!r} → {k!r} at leaf "
                    f"{i}: {lf!r}) — keep each variable's type stable "
                    "across iterations")
            out.append(lf.data if isinstance(lf, Tensor)
                       else (jnp.asarray(lf)
                             if not isinstance(lf, (jax.Array,
                                                    jax.core.Tracer))
                             else lf))
        for i in range(n):
            if kinds0[i] == 's' and leaves0[i] is not UNDEFINED:
                _check_statics(tag, [leaves0[i]], [leaves[i]])
        return out

    carry0 = extract_carry(leaves0, 'while')

    def cf(carry):
        set_state(to_state(carry))
        return to_bool(cond_fn())

    def bf(carry):
        set_state(to_state(carry))
        body_fn()
        leaves2, td2 = jax.tree_util.tree_flatten(
            get_state(), is_leaf=lambda t: isinstance(t, Tensor))
        if len(leaves2) != n or td2 != treedef:
            _check_match('while', treedef, kinds0, td2,
                         [kind_of(lf) for lf in leaves2])
        return extract_carry(leaves2, 'while')

    out = lax.while_loop(cf, bf, carry0)
    set_state(to_state(out))


def convert_while_loop(cond_fn, body_fn, get_state, set_state,
                       has_jump=False):
    """Parity: convert_operators.convert_while_loop — lax.while_loop when
    the condition is traced, Python loop otherwise (kept differentiable
    by unrolling). For loops with lowered break/continue (has_jump) whose
    STATE is traced, a traced branch can flip a jump flag mid-loop, so
    those run as lax.while_loop from the start (not reverse-
    differentiable — use python-condition jumps on training paths)."""
    from ..static.program import in_static_mode
    if in_static_mode() and _state_is_static(get_state()):
        # dispatch BEFORE evaluating cond_fn — a probe call would record
        # a dead compare op into the outer block
        return _static_while(cond_fn, body_fn, get_state, set_state)
    c0 = cond_fn()
    if _static_pred(c0):
        return _static_while(cond_fn, body_fn, get_state, set_state)
    if _is_traced(c0):
        return _run_lax_while(cond_fn, body_fn, get_state, set_state)
    if has_jump:
        leaves0, _ = jax.tree_util.tree_flatten(
            get_state(), is_leaf=lambda t: isinstance(t, Tensor))
        if any(isinstance(_raw(lf), jax.core.Tracer) for lf in leaves0):
            return _run_lax_while(cond_fn, body_fn, get_state, set_state)
    c = bool(np.asarray(_raw(c0)).reshape(()))
    while c:
        body_fn()
        c = to_bool(cond_fn())
        if isinstance(c, jax.core.Tracer):
            raise TypeError(
                "dy2static while: condition became a traced tensor "
                "after the first iteration — make it a tensor from "
                "the start so the loop converts to lax.while_loop")
    return


def normalize_range(*args):
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args[0], args[1], args[2]


def range_cond(i, stop, step):
    """i advancing by step still inside [start, stop)."""
    if _is_traced(i) or _is_traced(stop) or _is_traced(step):
        ri, rs, rp = _raw(i), _raw(stop), _raw(step)
        return jnp.where(rp > 0, ri < rs, ri > rs)
    return (i < stop) if step > 0 else (i > stop)


def _as_bool_arr(v):
    # mixed operands: one side may be a plain Python bool (e.g. a
    # break/continue flag before any traced assignment touches it)
    return jnp.asarray(_raw(v)).astype(bool)


def convert_logical_and(lhs_fn, rhs_fn):
    l = lhs_fn()
    if _is_traced(l):
        return Tensor(jnp.logical_and(_as_bool_arr(l),
                                      _as_bool_arr(rhs_fn())))
    return l and rhs_fn()      # Python value semantics: rhs unchanged


def convert_logical_or(lhs_fn, rhs_fn):
    l = lhs_fn()
    if _is_traced(l):
        return Tensor(jnp.logical_or(_as_bool_arr(l),
                                     _as_bool_arr(rhs_fn())))
    return l or rhs_fn()


def convert_logical_not(x):
    if _is_traced(x):
        return Tensor(jnp.logical_not(_as_bool_arr(x)))
    return not x


# ---- AST analysis helpers ---------------------------------------------------
class _AssignedNames(ast.NodeVisitor):
    """Names bound in a statement list (not descending into nested defs)."""

    def __init__(self):
        self.names = set()

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_comprehension(self, node):   # comp targets are scoped (py3)
        self.generic_visit(node)


def _assigned_names(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    # generated conversion helpers are not user state
    return sorted(n for n in v.names if not n.startswith('_pt_'))


class _HasUnsupported(ast.NodeVisitor):
    """Return anywhere in the subtree, or break/continue belonging to the
    converted construct itself (not to a nested loop)."""

    def __init__(self, loop_level=False):
        self.found = False
        self._loop_depth = 1 if loop_level else 0

    def visit_Return(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    def visit_For(self, node):
        self._loop(node)

    def visit_While(self, node):
        self._loop(node)

    def visit_With(self, node):
        self._other_block = getattr(self, '_other_block', 0) + 1
        self.generic_visit(node)
        self._other_block -= 1

    visit_AsyncWith = visit_With
    visit_Try = visit_With

    def visit_Break(self, node):
        # lowerable to flag vars only when this check runs for the
        # enclosing LOOP (depth >= 1) and the jump sits under plain If
        # nesting; under With/Try (or when checking an If body directly,
        # depth 0) the rewrite can't preserve semantics
        if getattr(self, '_other_block', 0) or self._loop_depth == 0:
            self.found = True

    def visit_Continue(self, node):
        if getattr(self, '_other_block', 0) or self._loop_depth == 0:
            self.found = True

    def visit_Global(self, node):
        self.found = True

    def visit_Nonlocal(self, node):
        self.found = True

    def visit_Attribute(self, node):
        # obj.attr = ... side effects cannot be threaded through lax.cond
        # (both branches trace; the write would leak) — keep Python flow
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.found = True
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.found = True
        self.generic_visit(node)


def _unsupported(stmts, loop_level=False):
    v = _HasUnsupported(loop_level=loop_level)
    v._loop_depth = 1 if loop_level else 0
    for s in stmts:
        v.visit(s)
    return v.found


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _jst_call(fname, args):
    return ast.Call(
        func=ast.Attribute(value=_load('_jst'), attr=fname, ctx=ast.Load()),
        args=args, keywords=[])


class _HasBreakContinue(ast.NodeVisitor):
    """break/continue binding to THIS loop (not nested ones)."""

    def __init__(self):
        self.found = False

    def visit_For(self, node):
        pass

    def visit_While(self, node):
        pass

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True


def _has_break_continue(stmts):
    v = _HasBreakContinue()
    for st in stmts:
        v.visit(st)
    return v.found


def _assign_const(name, value):
    return ast.Assign(targets=[_store(name)], value=ast.Constant(value))


def _lower_break_continue(stmts, brk, cont):
    """Rewrite break/continue into flag assignments with guarded
    fall-through (parity: break_continue_transformer.py). `break` sets
    `brk`, `continue` sets `cont`; statements after a construct that may
    have jumped are wrapped in `if not (brk or cont): ...` so both the
    Python path and the traced lax.cond path skip them. The loop itself
    adds `and not brk` to its condition and resets `cont` per iteration.

    Returns (new_stmts, may_jump)."""
    out = []
    for idx, st in enumerate(stmts):
        rest = stmts[idx + 1:]
        if isinstance(st, ast.Break):
            out.append(_assign_const(brk, True))
            return out, True          # rest is dead
        if isinstance(st, ast.Continue):
            out.append(_assign_const(cont, True))
            return out, True
        if isinstance(st, ast.If) and (_has_break_continue([st])):
            body2, bj = _lower_break_continue(st.body, brk, cont)
            orelse2, oj = _lower_break_continue(st.orelse, brk, cont)
            out.append(ast.If(test=st.test, body=body2,
                              orelse=orelse2 or []))
            rest2, rj = _lower_break_continue(rest, brk, cont)
            if rest2:
                # guard: if not (brk or cont): <rest>
                guard = ast.UnaryOp(
                    op=ast.Not(),
                    operand=ast.BoolOp(op=ast.Or(),
                                       values=[_load(brk), _load(cont)]))
                out.append(ast.If(test=guard, body=rest2, orelse=[]))
            return out, True
        out.append(st)
    return out, False


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _next(self):
        self._uid += 1
        return self._uid

    def _guards(self, names):
        """try: x  except NameError/UnboundLocalError: x = _jst.UNDEFINED"""
        out = []
        for n in names:
            out.append(ast.Try(
                body=[ast.Expr(value=_load(n))],
                handlers=[ast.ExceptHandler(
                    type=ast.Tuple(elts=[_load('NameError'),
                                         _load('UnboundLocalError')],
                                   ctx=ast.Load()),
                    name=None,
                    body=[ast.Assign(
                        targets=[_store(n)],
                        value=ast.Attribute(value=_load('_jst'),
                                            attr='UNDEFINED',
                                            ctx=ast.Load()))])],
                orelse=[], finalbody=[]))
        return out

    def _state_fns(self, uid, names):
        get_fn = ast.FunctionDef(
            name=f'_pt_get_{uid}', args=_no_args(),
            body=[ast.Return(value=ast.Tuple(
                elts=[_load(n) for n in names], ctx=ast.Load()))],
            decorator_list=[])
        set_body = []
        if names:
            set_body.append(ast.Nonlocal(names=list(names)))
            set_body.append(ast.Assign(
                targets=[ast.Tuple(elts=[_store(n) for n in names],
                                   ctx=ast.Store())],
                value=_load('_pt_vals')))
        else:
            set_body.append(ast.Pass())
        set_fn = ast.FunctionDef(
            name=f'_pt_set_{uid}', args=_one_arg('_pt_vals'),
            body=set_body, decorator_list=[])
        return get_fn, set_fn

    def _body_fn(self, name, names, body):
        fn_body = []
        if names:
            fn_body.append(ast.Nonlocal(names=list(names)))
        fn_body.extend(body if body else [])
        if not fn_body:
            fn_body = [ast.Pass()]
        return ast.FunctionDef(name=name, args=_no_args(), body=fn_body,
                               decorator_list=[])

    # -- if --------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _unsupported(node.body) or _unsupported(node.orelse):
            return node
        uid = self._next()
        names = _assigned_names(node.body + node.orelse)
        true_fn = self._body_fn(f'_pt_true_{uid}', names, node.body)
        false_fn = self._body_fn(f'_pt_false_{uid}', names, node.orelse)
        get_fn, set_fn = self._state_fns(uid, names)
        call = ast.Expr(value=_jst_call('convert_ifelse', [
            node.test, _load(true_fn.name), _load(false_fn.name),
            _load(get_fn.name), _load(set_fn.name)]))
        return self._guards(names) + [true_fn, false_fn, get_fn, set_fn,
                                      call]

    # -- while -----------------------------------------------------------
    def visit_While(self, node, extra_tail=None):
        if node.orelse or _unsupported(node.body, loop_level=True):
            self.generic_visit(node)
            return node
        pre = []
        has_jump = False
        if _has_break_continue(node.body):
            has_jump = True
            uid_bc = self._next()
            brk = f'_ds_brk_{uid_bc}'
            cont = f'_ds_cont_{uid_bc}'
            body2, _ = _lower_break_continue(list(node.body), brk, cont)
            tail = list(extra_tail or [])
            node = ast.While(
                test=ast.BoolOp(op=ast.And(), values=[
                    ast.UnaryOp(op=ast.Not(), operand=_load(brk)),
                    node.test]),
                body=[_assign_const(cont, False)] + body2 + tail,
                orelse=[])
            pre = [_assign_const(brk, False), _assign_const(cont, False)]
        elif extra_tail:
            node = ast.While(test=node.test,
                             body=list(node.body) + list(extra_tail),
                             orelse=[])
        ast.fix_missing_locations(node)
        self.generic_visit(node)
        uid = self._next()
        names = _assigned_names(node.body)
        cond_fn = ast.FunctionDef(
            name=f'_pt_wcond_{uid}', args=_no_args(),
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = self._body_fn(f'_pt_wbody_{uid}', names, node.body)
        get_fn, set_fn = self._state_fns(uid, names)
        call = ast.Expr(value=_jst_call('convert_while_loop', [
            _load(cond_fn.name), _load(body_fn.name),
            _load(get_fn.name), _load(set_fn.name),
            ast.Constant(value=has_jump)]))
        return pre + self._guards(names) + [cond_fn, body_fn, get_fn,
                                            set_fn, call]

    # -- for range(...) ----------------------------------------------------
    def visit_For(self, node):
        if not (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == 'range'
                and isinstance(node.target, ast.Name)
                and not node.orelse
                and not _unsupported(node.body, loop_level=True)):
            self.generic_visit(node)
            return node
        uid = self._next()
        i = node.target.id
        # hidden induction counter (`_ds_` so it stays in loop state):
        # the user variable is assigned FROM it each iteration, so body
        # reassignments of the loop var can't corrupt iteration and its
        # post-loop value matches Python's (last yielded value)
        ctr = f'_ds_i_{uid}'
        start, stop, step = (f'_pt_start_{uid}', f'_pt_stop_{uid}',
                             f'_pt_step_{uid}')
        setup = ast.Assign(
            targets=[ast.Tuple(elts=[_store(start), _store(stop),
                                     _store(step)], ctx=ast.Store())],
            value=_jst_call('normalize_range', list(node.iter.args)))
        init = ast.Assign(
            targets=[ast.Tuple(elts=[_store(ctr), _store(i)],
                               ctx=ast.Store())],
            value=ast.Tuple(elts=[_load(start), _load(start)],
                            ctx=ast.Load()))
        take = ast.Assign(targets=[_store(i)], value=_load(ctr))
        bump = ast.Assign(
            targets=[_store(ctr)],
            value=ast.BinOp(left=_load(ctr), op=ast.Add(),
                            right=_load(step)))
        # bump rides as extra_tail: with break/continue it must run
        # OUTSIDE the lowered guards (continue still advances the
        # induction var; break exits via the loop condition)
        loop = ast.While(
            test=_jst_call('range_cond',
                           [_load(ctr), _load(stop), _load(step)]),
            body=[take] + list(node.body), orelse=[])
        loop_out = self.visit_While(loop, extra_tail=[bump])
        if not isinstance(loop_out, list):
            loop_out = [loop_out]
        return [setup, init] + loop_out

    # -- and/or/not --------------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fname = 'convert_logical_and' if isinstance(node.op, ast.And) \
            else 'convert_logical_or'
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = _jst_call(fname, [
                ast.Lambda(args=_no_args_lambda(), body=v),
                ast.Lambda(args=_no_args_lambda(), body=out)])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call('convert_logical_not', [node.operand])
        return node

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        # our own injected dispatchers stay bare
        if isinstance(f, ast.Attribute) and                 isinstance(f.value, ast.Name) and f.value.id == '_jst':
            return node
        # super() must keep its zero-arg magic (cell access)
        if isinstance(f, ast.Name) and f.id == 'super':
            return node
        return ast.Call(func=_jst_call('convert_call', [f]),
                        args=node.args, keywords=node.keywords)


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _one_arg(name):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=name)],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def _no_args_lambda():
    return _no_args()


def final_return(v):
    """The fall-off-the-end path returns None (Python semantics)."""
    return None if v is UNDEFINED else v


_NO_CONVERT_MODULE_PREFIXES = ('paddle_tpu', 'jax', 'numpy', 'builtins',
                               'functools', 'itertools', 'math', 'torch')


import weakref

_converted_fn_cache = weakref.WeakKeyDictionary()


def convert_call(f):
    """Parity: convert_call_func.py convert_call — recursively convert
    callees at the call site. Framework/library callables pass through;
    plain user functions and methods get the same AST conversion as the
    entry function. Plain functions cache their converted form (keyed on
    the function object, revalidated on closure-cell identity); bound
    methods reconvert per call (the method object is fresh each access,
    but the factory underneath is cached per code object)."""
    if not callable(f):
        return f
    mod = getattr(f, '__module__', None) or ''
    if any(mod == p or mod.startswith(p + '.')
           for p in _NO_CONVERT_MODULE_PREFIXES):
        return f
    if inspect.isclass(f) or inspect.isbuiltin(f):
        return f
    if inspect.isfunction(f) and getattr(f, '__self__', None) is None:
        cells = tuple(id(c) for c in (f.__closure__ or ()))
        hit = _converted_fn_cache.get(f)
        if hit is not None and hit[0] == cells:
            return hit[1]
        try:
            conv = convert_function(f)
        except Exception:
            conv = f
        try:
            _converted_fn_cache[f] = (cells, conv)
        except TypeError:
            pass
        return conv
    if inspect.ismethod(f):
        try:
            return convert_function(f)
        except Exception:
            return f
    return f


class _ReturnInIf(ast.NodeVisitor):
    """Is there a Return directly inside an If branch (recursing through
    nested Ifs but not loops/defs)? Those are the returns we lower."""

    def __init__(self):
        self.found = False

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_For(self, node):
        pass

    def visit_While(self, node):
        pass

    def visit_Return(self, node):
        self.found = True


def _needs_return_lowering(stmts):
    for s in stmts:
        if isinstance(s, ast.If):
            v = _ReturnInIf()
            v.generic_visit(s)
            if v.found:
                return True
    return False


def _lower_returns(stmts):
    """Rewrite `return e` into `_ds_ret = e`, merging the statements that
    follow an if into whichever branch falls through (parity:
    return_transformer.py — linear for guard-clause chains; duplicated
    trace for genuinely diamond-shaped flow, which XLA CSEs away).

    Returns (new_stmts, always_returns)."""
    out = []
    for idx, s in enumerate(stmts):
        rest = stmts[idx + 1:]
        if isinstance(s, ast.Return):
            val = s.value if s.value is not None else \
                ast.Constant(value=None)
            out.append(ast.Assign(targets=[_store('_ds_ret')], value=val))
            return out, True          # following stmts are dead
        if isinstance(s, ast.If):
            v = _ReturnInIf()
            v.generic_visit(s)
            if v.found:
                body2, bret = _lower_returns(s.body)
                orelse2, oret = _lower_returns(s.orelse)
                rest2, rret = _lower_returns(rest)
                if not bret:
                    body2 = body2 + rest2
                if not oret:
                    orelse2 = orelse2 + rest2
                out.append(ast.If(test=s.test, body=body2,
                                  orelse=orelse2 or [ast.Pass()]))
                return out, (bret or rret) and (oret or rret)
        out.append(s)
    return out, False


# ---- function conversion ----------------------------------------------------
_factory_cache = {}


def convert_function(fn):
    """Rewrite `fn`'s control flow; returns a new function with the same
    closure/globals, or `fn` unchanged when the source is unavailable or
    contains nothing convertible. Parity: ProgramTranslator's
    to-static conversion of the decorated callable.

    The transformed/compiled factory is cached per code object, but the
    factory is re-applied to EACH function's own closure cells — two
    closures sharing code get their own values (cell contents are
    snapshotted at conversion time)."""
    from .. import profiler as _prof
    from ..core.monitor import counter
    base = getattr(fn, '__func__', fn)
    key = getattr(base, '__code__', None)
    if key in _factory_cache:
        factory = _factory_cache[key]
        counter('ptpu_dy2static_conversions_total',
                help='AST control-flow conversions',
                labelnames=('result',)).inc(1, result='cached')
    else:
        with _prof.RecordEvent('dy2static::ast_transform',
                               event_type='compile',
                               fn=getattr(base, '__qualname__', '?')):
            factory = _build_factory(base)
        _factory_cache[key] = factory
        counter('ptpu_dy2static_conversions_total',
                help='AST control-flow conversions',
                labelnames=('result',)).inc(
                    1, result='converted' if factory else 'passthrough')
    if factory is None:
        return fn
    try:
        cells = [c.cell_contents for c in (base.__closure__ or ())]
        conv = factory(*cells)
    except Exception:
        return fn
    conv.__defaults__ = base.__defaults__
    conv.__kwdefaults__ = base.__kwdefaults__
    conv = functools.wraps(base)(conv)
    if getattr(fn, '__self__', None) is not None:   # rebind methods
        return conv.__get__(fn.__self__)
    return conv


def _build_factory(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = next((n for n in tree.body
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))), None)
    if fdef is None:
        return None
    fdef.decorator_list = []
    if _needs_return_lowering(fdef.body):
        fdef.body, _ = _lower_returns(fdef.body)
        fdef.body.insert(0, ast.Assign(
            targets=[_store('_ds_ret')],
            value=ast.Attribute(value=_load('_jst'), attr='UNDEFINED',
                                ctx=ast.Load())))
        fdef.body.append(ast.Return(
            value=_jst_call('final_return', [_load('_ds_ret')])))
    _ControlFlowTransformer().visit(fdef)
    ast.fix_missing_locations(tree)

    freevars = fn.__code__.co_freevars
    factory_name = f'_pt_factory_{fn.__name__}'
    factory = ast.FunctionDef(
        name=factory_name,
        args=ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in freevars],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=[fdef, ast.Return(value=_load(fdef.name))],
        decorator_list=[])
    mod = ast.Module(body=[factory], type_ignores=[])
    ast.fix_missing_locations(mod)

    import sys
    # the LIVE module globals, not a snapshot: later rebinding of a
    # module-level name is visible to the converted function exactly as
    # to the eager one (ADVICE r2; the reference resolves through the
    # live function object). `_jst` is injected; on the (pathological)
    # collision with a user global of that name we fall back to a copy.
    ours = sys.modules[__name__]
    glb = fn.__globals__
    if glb.get('_jst', ours) is not ours:
        glb = dict(fn.__globals__)
    glb['_jst'] = ours
    try:
        code = compile(mod, filename=f'<dy2static {fn.__qualname__}>',
                       mode='exec')
        ns = {}
        exec(code, glb, ns)
        return ns[factory_name]
    except Exception:
        return None
