"""paddle_tpu.autograd — PyLayer + backward.

Reference parity: python/paddle/autograd (py_layer.py:21 PyLayer — user
fwd/bwd, the substrate for recompute) and paddle.autograd.backward.
"""
from ..core.autograd import backward as _backward, no_grad, enable_grad
from ..core.autograd import record, run_op
from ..core.tensor import Tensor
from ..framework import grad


def backward(tensors, grad_tensors=None, retain_graph=False):
    _backward(tensors, grad_tensors, retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Parity: paddle.autograd.PyLayer (py_layer.py:21/192).

    Subclass with @staticmethod forward(ctx, *args) / backward(ctx, *grads);
    apply() records one tape node whose vjp calls user backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import autograd as ag
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        needs = [not t.stop_gradient for t in tensor_args]
        if ag.grad_enabled() and any(needs):
            def vjp_fn(cts):
                cts_list = list(cts) if isinstance(cts, tuple) else [cts]
                ct_tensors = [Tensor(c, stop_gradient=True)
                              for c in cts_list]
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                gin = list(gin) if isinstance(gin, (tuple, list)) else [gin]
                out_grads = []
                gi = iter(gin)
                for a in tensor_args:
                    g = next(gi, None)
                    out_grads.append(None if g is None else g.data)
                return out_grads

            detached = []
            for t in outs:
                nt = Tensor(t.data, stop_gradient=False)
                detached.append(nt)
            record(cls.__name__, lambda ct: vjp_fn(ct), tensor_args, needs,
                   detached)
            outs = detached
        return tuple(outs) if multi else outs[0]


class PyLayerContext_:  # legacy alias
    pass


LegacyPyLayer = PyLayer
