"""paddle.regularizer (parity: python/paddle/regularizer.py — L1Decay/
L2Decay applied per-param via ParamAttr.regularizer or optimizer
weight_decay)."""


class WeightDecayRegularizer:
    def __call__(self, param_array):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __call__(self, param_array):
        return self._coeff * param_array

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __call__(self, param_array):
        import jax.numpy as jnp
        return self._coeff * jnp.sign(param_array)

    def __repr__(self):
        return f"L1Decay({self._coeff})"
