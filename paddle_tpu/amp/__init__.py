"""AMP — automatic mixed precision.

Reference parity: python/paddle/amp (auto_cast with WHITE/BLACK lists
dygraph/amp/auto_cast.py:27-52; GradScaler grad_scaler.py:20 ← AmpScaler
loss_scaler.py:28 with dynamic loss scaling driven by
check_finite_and_unscale + update_loss_scaling ops, operators/amp/).

TPU-native notes: bf16 is the native mixed-precision dtype — it shares fp32's
exponent range, so loss scaling is mathematically unnecessary; GradScaler
keeps full API parity (dynamic scale bookkeeping included) and is a cheap
no-op-ish path when dtype='bfloat16'.
"""
import contextlib

import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor
from ..core.autograd import no_grad

# Parity: dygraph/amp/auto_cast.py:27-52
WHITE_LIST = {'conv2d', 'matmul', 'matmul_v2', 'mul', 'linear',
              'fused_attention'}
BLACK_LIST = {'exp', 'square', 'log', 'mean', 'sum', 'cos_sim', 'softmax',
              'softmax_with_cross_entropy', 'sigmoid_cross_entropy_with_logits',
              'cross_entropy', 'cross_entropy2', 'reduce_sum',
              'reduce_mean', 'layer_norm', 'batch_norm'}

_amp_state = {'enabled': False, 'dtype': jnp.bfloat16, 'level': 'O1',
              'custom_white': set(), 'custom_black': set()}


def amp_state():
    return _amp_state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level='O1', dtype='bfloat16'):
    """Parity: paddle.amp.auto_cast. Ops in the white list run in bf16/fp16;
    black-list ops run fp32; others follow their inputs (O1). O2 casts
    everything except black-list."""
    saved = dict(_amp_state)
    _amp_state.update(
        enabled=enable, level=level,
        dtype=dtypes.convert_dtype(dtype),
        custom_white=set(custom_white_list or ()),
        custom_black=set(custom_black_list or ()))
    try:
        yield
    finally:
        _amp_state.update(saved)


amp_guard = auto_cast


def _should_cast_to_low(op_name):
    if not _amp_state['enabled']:
        return None
    white = (WHITE_LIST | _amp_state['custom_white']) - _amp_state['custom_black']
    black = (BLACK_LIST | _amp_state['custom_black']) - _amp_state['custom_white']
    if op_name in white:
        return True
    if op_name in black:
        return False
    if _amp_state['level'] == 'O2':
        return True
    return None  # follow inputs


def maybe_autocast_args(op_name, tensors):
    """Called from the op layer: cast float inputs per the amp lists."""
    decision = _should_cast_to_low(op_name)
    if decision is None:
        return tensors
    target = _amp_state['dtype'] if decision else jnp.float32
    from ..ops import manip
    out = []
    for t in tensors:
        if dtypes.is_floating(t.data.dtype) and t.data.dtype != target:
            out.append(manip.cast(t, target))
        else:
            out.append(t)
    return out


class GradScaler:
    """Parity: paddle.amp.GradScaler (grad_scaler.py:20 / AmpScaler
    loss_scaler.py:28): dynamic loss scaling with incr/decr_every_n."""

    def __init__(self, enable=True, init_loss_scaling=2.**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def scale(self, var):
        if not self._enable:
            return var
        from ..ops import math as M
        return M.scale(var, self._scale)

    def unscale_(self, optimizer):
        """Parity: check_finite_and_unscale (operators/amp/...cc:138).

        Bucketed (ISSUE 4): grads flatten into the dtype-homogeneous
        buckets of core/bucketing.py, the unscale multiply and the
        finite check run per BUCKET (a handful of fused kernels instead
        of one chain per parameter), and a SINGLE host sync — routed
        through the numerics observatory's fetch hook so tests can
        count it — reads the verdict (the seed synced once per
        parameter — a per-step latency cliff at transformer param
        counts)."""
        if not self._enable or self._unscaled:
            return
        params = optimizer._parameter_list or []
        grads = [p.grad for p in params if p.grad is not None]
        if not grads:
            self._found_inf = False
            self._unscaled = True
            return
        from ..core import bucketing as B
        from ..core import numerics as _num
        inv = 1.0 / self._scale
        layout, flats = B.flatten_grad_list(grads)
        flags, out = [], []
        for f in flats:
            f32 = f.astype(jnp.float32) * inv
            flags.append(jnp.any(~jnp.isfinite(f32)))
            out.append(f32)
        unflat = layout.unflatten(out)
        for i, g in enumerate(grads):
            g.data = unflat[str(i)].astype(g.data.dtype)
        self._found_inf = bool(_num._host_fetch(
            jnp.any(jnp.stack(flags))))
        self._unscaled = True

    def _publish_metrics(self, skipped):
        from ..core import monitor as _m
        _m.counter('ptpu_amp_steps_total',
                   help='GradScaler.step() calls').inc(1)
        if skipped:
            _m.counter('ptpu_amp_skipped_steps_total',
                       help='optimizer updates skipped on nonfinite '
                            'gradients').inc(1)
        _m.gauge('ptpu_amp_loss_scale',
                 help='current dynamic loss scale').set(self._scale)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        try:
            self.unscale_(optimizer)
            if not self._found_inf:
                optimizer.step()
            else:
                # skipped update: optimizer.step() never runs, so the
                # eager numerics guard's step boundary never flushes —
                # drop the (deliberately survived) overflow's flag and
                # journal here, or the NEXT clean step would raise for
                # THIS one
                from ..core import numerics as _numerics
                _numerics.guard().reset()
            self._update()
            self._publish_metrics(self._found_inf)
        finally:
            # always re-arm: a NumericsError escaping optimizer.step()
            # must not leave _unscaled latched True, or every later
            # step would skip unscale_ and apply still-scaled grads
            self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        return [], []

    def update_from_found(self, found):
        """Deferred found-inf accounting for the async engine path
        (ISSUE 13, docs/performance.md#async-dispatch): one drained
        step's verdict drives the dynamic-scale schedule, applied in
        window-drain (= submission) order — the same sequence the
        per-step path (`scaler._found_inf = ...; scaler._update()`)
        applies for the scales actually dispatched, just read at the
        drain point instead of blocking the dispatch hot loop. Note the
        documented lag: a scale CHANGE only reaches steps dispatched
        after its drain (up to `window` steps later than the per-step
        path), so scale-induced overflows can resolve one window late.
        The compiled step already skipped the update device-side; this
        is only the host bookkeeping."""
        if not self._enable:
            return
        self._found_inf = bool(found)
        self._update()
        self._publish_metrics(self._found_inf)

    def update(self):
        pass  # folded into step() like AmpScaler.minimize

    def _update(self):
        """Parity: update_loss_scaling op."""
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        """Parity: paddle.amp.GradScaler.state_dict — the loss-scale
        schedule state a checkpoint must carry (losing it on restore
        resets the scale to init and replays the warm-up overflows).
        Uses paddle's incr_count/decr_count key names; good_steps/
        bad_steps are kept as aliases for older checkpoints."""
        return {'scale': self._scale, 'incr_ratio': self._incr_ratio,
                'decr_ratio': self._decr_ratio,
                'incr_every_n_steps': self._incr_every_n,
                'decr_every_n_nan_or_inf': self._decr_every_n,
                'incr_count': self._good_steps,
                'decr_count': self._bad_steps,
                'good_steps': self._good_steps, 'bad_steps': self._bad_steps,
                'use_dynamic_loss_scaling': self._dynamic,
                'enable': self._enable}

    def set_state_dict(self, sd):
        self._scale = float(sd.get('scale', self._scale))
        self._incr_ratio = float(sd.get('incr_ratio', self._incr_ratio))
        self._decr_ratio = float(sd.get('decr_ratio', self._decr_ratio))
        self._incr_every_n = int(sd.get('incr_every_n_steps',
                                        self._incr_every_n))
        self._decr_every_n = int(sd.get('decr_every_n_nan_or_inf',
                                        self._decr_every_n))
        self._good_steps = int(sd.get('incr_count',
                                      sd.get('good_steps', 0)))
        self._bad_steps = int(sd.get('decr_count', sd.get('bad_steps', 0)))
        self._dynamic = bool(sd.get('use_dynamic_loss_scaling',
                                    self._dynamic))
        # 'enable' is saved for inspection only and deliberately NOT
        # restored: silently disabling loss scaling on an enabled
        # scaler (checkpoint from a debug run) would apply unscaled
        # fp16 grads with no overflow skipping

    # torch-style alias (paddle 2.x accepts both spellings in hapi)
    load_state_dict = set_state_dict


def decorate(models=None, optimizers=None, level='O2', dtype='bfloat16',
             master_weight=None, save_dtype=None):
    """Parity: paddle.amp.decorate — casts model params to the amp dtype for
    O2 (pure bf16/fp16) training; optimizers keep fp32 master weights."""
    target = dtypes.convert_dtype(dtype)
    def _cast_model(m):
        for p in m.parameters():
            if dtypes.is_floating(p.dtype):
                p.data = p.data.astype(target)
        return m
    if models is None:
        return None
    single_model = not isinstance(models, (list, tuple))
    ms = [models] if single_model else list(models)
    ms = [_cast_model(m) for m in ms]
    if optimizers is None:
        return ms[0] if single_model else ms
    return (ms[0] if single_model else ms), optimizers
