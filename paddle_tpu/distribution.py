"""paddle.distribution (parity: fluid/layers/distributions.py + the 2.x
paddle.distribution package: Normal, Uniform, Categorical, Beta,
Multinomial-lite)."""
import math

import jax
import jax.numpy as jnp

from .core import rng as rng_mod
from .core.tensor import Tensor
from .ops.common import as_tensor


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        from .ops import math as M
        return M.exp(self.log_prob(value))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = as_tensor(low)
        self.high = as_tensor(high)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key()
        shp = tuple(shape) + tuple(self.low.shape)
        u = jax.random.uniform(key, shp)
        return Tensor(self.low.data + u * (self.high.data - self.low.data))

    def log_prob(self, value):
        value = as_tensor(value)
        from .ops import math as M
        inside = (value.data >= self.low.data) & (value.data < self.high.data)
        lp = jnp.where(inside,
                       -jnp.log(self.high.data - self.low.data), -jnp.inf)
        return Tensor(lp)

    def entropy(self):
        return Tensor(jnp.log(self.high.data - self.low.data))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc)
        self.scale = as_tensor(scale)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key()
        shp = tuple(shape) + tuple(self.loc.shape)
        return Tensor(self.loc.data
                      + self.scale.data * jax.random.normal(key, shp))

    def log_prob(self, value):
        value = as_tensor(value)
        var = self.scale.data ** 2
        return Tensor(-((value.data - self.loc.data) ** 2) / (2 * var)
                      - jnp.log(self.scale.data)
                      - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale.data))

    def kl_divergence(self, other):
        var_ratio = (self.scale.data / other.scale.data) ** 2
        t1 = ((self.loc.data - other.loc.data) / other.scale.data) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = as_tensor(logits)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key()
        batch = tuple(self.logits.shape[:-1])
        shp = tuple(shape) + batch
        return Tensor(jax.random.categorical(key, self.logits.data,
                                             shape=shp or None))

    def log_prob(self, value):
        value = as_tensor(value)
        logp = jax.nn.log_softmax(self.logits.data, axis=-1)
        return Tensor(jnp.take_along_axis(
            logp, value.data.astype(jnp.int32)[..., None], axis=-1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits.data, axis=-1)
        p = jnp.exp(logp)
        return Tensor(-jnp.sum(p * logp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = as_tensor(alpha)
        self.beta = as_tensor(beta)

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key()
        shp = tuple(shape) + tuple(self.alpha.shape)
        return Tensor(jax.random.beta(key, self.alpha.data, self.beta.data,
                                      shp))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = as_tensor(value).data
        a, b = self.alpha.data, self.beta.data
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                      - betaln(a, b))


class MultivariateNormalDiag(Distribution):
    """fluid.layers.distributions MultivariateNormalDiag
    (distributions.py:531): loc [.., d], scale [.., d, d] with only the
    diagonal consulted (the reference's contract)."""

    def __init__(self, loc, scale, name=None):
        self.loc = as_tensor(loc)
        self.scale = as_tensor(scale)

    def _diag(self):
        d = self.scale.data
        return jnp.diagonal(d, axis1=-2, axis2=-1) if d.ndim >= 2 else d

    def sample(self, shape=(), seed=0):
        key = rng_mod.next_key()
        shp = tuple(shape) + tuple(self.loc.shape)
        eps = jax.random.normal(key, shp, self.loc.data.dtype)
        return Tensor(self.loc.data + self._diag() * eps)

    def log_prob(self, value):
        v = as_tensor(value).data
        sig = self._diag()
        z = (v - self.loc.data) / sig
        return Tensor((-0.5 * z * z - jnp.log(sig)
                       - 0.5 * math.log(2 * math.pi)).sum(-1))

    def entropy(self):
        """Reference formula (distributions.py:598): d/2 (1 + log(2π))
        + 1/2 log det(diag(σ²))."""
        sig = self._diag()
        d = sig.shape[-1]
        return Tensor(0.5 * d * (1.0 + math.log(2 * math.pi))
                      + jnp.log(sig * sig).sum(-1) * 0.5)

    def kl_divergence(self, other):
        """Diag-Gaussian KL (reference distributions.py:616)."""
        s1, s2 = self._diag(), other._diag()
        var1, var2 = s1 * s1, s2 * s2
        dmu = self.loc.data - other.loc.data
        return Tensor(0.5 * (
            (var1 / var2).sum(-1)
            + (dmu * dmu / var2).sum(-1)
            - s1.shape[-1]
            + jnp.log(var2).sum(-1) - jnp.log(var1).sum(-1)))


def kl_divergence(p, q):
    if isinstance(p, Normal) and isinstance(q, Normal):
        return p.kl_divergence(q)
    if isinstance(p, MultivariateNormalDiag) and \
            isinstance(q, MultivariateNormalDiag):
        return p.kl_divergence(q)
    raise NotImplementedError(type(p))
