"""Per-request lifecycle tracing for the serving engine.

Answers "why was THIS request slow": every request carries a capped
event journal — submit / admit / prefill_chunk / first_token / decode
(per-iteration participation) / preempt / resume / retire / abort —
with monotonic timestamps and the request's page-table size at the
time. All of it is pure host bookkeeping on data the scheduler already
holds: tracing adds ZERO device work and zero extra host syncs
(asserted in tests/test_serving_trace.py).

Exports:

  * JSON-lines (`RequestTracer.export_jsonl`) — one event per line,
    schema header first; `load_trace()` round-trips it and
    `reconstruct()` derives the per-request SLO table (queue-wait,
    TTFT, TPOT, e2e, preemptions, pages high-water) that
    tools/trace_summary.py renders;
  * chrome-trace (`RequestTracer.export_chrome_tracing`) via the PR-1
    profiler writers — each request renders as its own track (synthetic
    tid) next to the engine's serve::* step spans, so "request 7 sat
    preempted while the batch decoded" is visible in Perfetto.

The stalled-request watchdog (engine.py) snapshots a request's journal
plus the scheduler-timeline tail and a pool census into a structured
`serve_report` JSON artifact through the PR-2 log_util conventions;
`render_serve_report()` is the human renderer health_dump.py uses.
"""
import collections
import json
import os
import threading
import time

SCHEMA = 'paddle_tpu.serve_trace/6'
# older files still load — load_trace accepts /1 (no route events),
# /2 (no tenancy/degradation events), /3 (no goodput pricing), /4
# (no fused decode), /5 (no host tier) and /6
SCHEMAS = ('paddle_tpu.serve_trace/1', 'paddle_tpu.serve_trace/2',
           'paddle_tpu.serve_trace/3', 'paddle_tpu.serve_trace/4',
           'paddle_tpu.serve_trace/5',
           SCHEMA)

# lifecycle event vocabulary (docs/serving.md#request-traces);
# prefix_hit = cached pages mapped at prefill start (ISSUE 9),
# spec_verify = one speculative verify outcome (k proposed, m accepted),
# route = cluster-router placement (ISSUE 11, schema v2: replica_id +
# router_decision affinity|least_loaded|spill — stamped by the replica
# worker right after submit so per-replica trace files say who placed
# the request here and why). Schema v3 (ISSUE 15): submit carries
# tenant_id/priority/deadline_s, quota_defer marks a quota-deferred
# admission episode, deadline_miss a finish past the request's own
# deadline, and degrade_stage — recorded under the engine-scope
# pseudo-request ENGINE_REQ — a degradation-ladder transition.
# Schema v4 (ISSUE 17) adds FIELDS only, no new events: prefill_chunk
# carries `recompute_tokens` when the chunk re-derives positions a
# preemption destroyed (pricing the request's wasted work in place)
# and `sampled` when the chunk completes prefill and samples a token
# off its final column; spec_verify carries `discarded` for the
# accepted-but-dropped burst tail. reconstruct() folds them (with
# rejected spec drafts) into per-request delivered/wasted columns.
# Schema v5 (ISSUE 19) adds fused_decode: one per request per fused
# k-iteration window, carrying `k` (window length) and `accepted`
# (tokens the request took before eos/budget idled it) — the fused
# counterpart of `decode`, which stays per serial iteration.
# Schema v6 (ISSUE 20) adds the host-tier pair: `spill` — recorded
# under ENGINE_REQ like degrade_stage, one per engine step that moved
# pages device->host, carrying `pages` and `host_used_pages` — and
# `resurrect`, a per-request event at prefill start when a prefix
# match landed host-tier pages back on device instead of re-running
# prefill, carrying `pages` and `tokens` (the re-prefill compute the
# fetch replaced).
EVENTS = ('submit', 'route', 'admit', 'prefix_hit', 'prefill_chunk',
          'first_token', 'decode', 'fused_decode', 'spec_verify',
          'preempt', 'resume',
          'quota_defer', 'deadline_miss', 'degrade_stage',
          'spill', 'resurrect',
          'retire', 'abort')

# engine-scope events (ladder transitions) journal under this pseudo
# request id: they export/load like any event but reconstruct() skips
# negative ids — they describe the ENGINE's state, not a request's
ENGINE_REQ = -1

# chrome-trace: request tracks live on a 'serving requests'
# pseudo-process (one virtual thread per request) beside the host
# process's engine spans — same timeline, clearly grouped
_TRACK_PID = 1 << 22
_TRACK_PNAME = 'serving requests'
_TRACK_TID_BASE = 1 << 24


class RequestTrace:
    """Capped per-request event journal. Events beyond `cap` are
    counted in `dropped` instead of appended — a runaway decode can't
    grow host memory without bound."""

    __slots__ = ('req_id', 'events', 'cap', 'dropped')

    def __init__(self, req_id, cap=512):
        self.req_id = req_id
        self.events = []
        self.cap = max(1, int(cap))   # room for the terminal event
        self.dropped = 0

    def add(self, event, t, **fields):
        if len(self.events) >= self.cap:
            if event in ('retire', 'abort'):
                # the terminal event is load-bearing (end state, e2e,
                # authoritative token count) — evict the newest
                # interior event instead of dropping the end of life
                if self.events:
                    self.events.pop()
                    self.dropped += 1
            else:
                self.dropped += 1
                return
        e = {'req': self.req_id, 'event': event, 't': float(t)}
        if fields:
            e.update(fields)
        self.events.append(e)


class RequestTracer:
    """Journal registry: live requests plus a ring of the most recently
    retired ones (`capacity_requests`), so a long-lived engine's trace
    memory is bounded. `clock` is injectable for deterministic tests —
    the engine shares ONE clock between tracer, scheduler and SLO
    accounting so cross-source timestamps compare exactly."""

    def __init__(self, capacity_requests=512, events_per_request=512,
                 clock=None):
        self.capacity_requests = int(capacity_requests)
        self.events_per_request = int(events_per_request)
        self.clock = clock or time.perf_counter
        self._live = {}                        # req_id -> RequestTrace
        self._done = collections.deque(maxlen=self.capacity_requests)
        self._lock = threading.Lock()
        self.dropped_requests = 0

    # -- recording -----------------------------------------------------------
    def record(self, req_id, event, t=None, **fields):
        """Append an event; pass `t` when the caller already stamped
        the moment (engine submit/first-token/finish times) so the
        journal's timestamp is bit-identical to the engine's — the
        reconstruction-equals-engine tests rely on it."""
        if t is None:
            t = self.clock()
        with self._lock:
            tr = self._live.get(req_id)
            if tr is None:
                tr = self._live[req_id] = RequestTrace(
                    req_id, cap=self.events_per_request)
            tr.add(event, t, **fields)
            if event in ('retire', 'abort'):
                self._live.pop(req_id, None)
                if len(self._done) == self._done.maxlen:
                    self.dropped_requests += 1
                self._done.append(tr)
        return t

    def reset(self):
        with self._lock:
            self._live.clear()
            self._done.clear()
            self.dropped_requests = 0

    # -- reading -------------------------------------------------------------
    def traces(self):
        """Every journal (retired ring first, then live), oldest first."""
        with self._lock:
            return list(self._done) + list(self._live.values())

    def events(self, req_id=None):
        out = []
        for tr in self.traces():
            if req_id is None or tr.req_id == req_id:
                out.extend(tr.events)
        out.sort(key=lambda e: e['t'])
        return out

    def request_table(self):
        return reconstruct(self.events())

    # -- exporters -----------------------------------------------------------
    def export_jsonl(self, path):
        """JSON-lines: a schema header line, then one event per line in
        timestamp order."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        dropped = sum(tr.dropped for tr in self.traces())
        with open(path, 'w') as f:
            f.write(json.dumps({'schema': SCHEMA,
                                'dropped_events': dropped,
                                'dropped_requests':
                                    self.dropped_requests}) + '\n')
            for e in self.events():
                f.write(json.dumps(e) + '\n')
        return path

    def chrome_spans(self):
        """Profiler-internal span dicts (one virtual thread per
        request): lifecycle segments between consecutive events —
        queued / prefill / decode / preempted — plus zero-duration
        markers for first_token and retire/abort. Feed them to
        profiler's chrome writer next to the engine's serve::* spans."""
        spans = []
        for tr in self.traces():
            tid = _TRACK_TID_BASE + (tr.req_id if tr.req_id >= 0
                                     else (1 << 23))
            tname = (f'req {tr.req_id}' if tr.req_id >= 0
                     else 'engine (degradation ladder)')
            evs = tr.events
            for i, e in enumerate(evs):
                t_us = int(e['t'] * 1e6)
                nxt_us = (int(evs[i + 1]['t'] * 1e6)
                          if i + 1 < len(evs) else t_us)
                ev, seg = e['event'], None
                if ev in ('submit', 'preempt'):
                    seg = 'queued' if ev == 'submit' else 'preempted'
                elif ev in ('admit', 'resume'):
                    seg = 'prefill'
                elif ev in ('prefill_chunk', 'first_token', 'decode'):
                    seg = ev
                if seg is not None and nxt_us > t_us:
                    spans.append({
                        'name': f'{tr.req_id}:{seg}',
                        'cat': 'serve_request', 'ts': t_us,
                        'dur': nxt_us - t_us, 'tid': tid, 'tname': tname,
                        'pid': _TRACK_PID, 'pname': _TRACK_PNAME,
                        'args': {k: v for k, v in e.items()
                                 if k not in ('t',)}})
                if ev in ('first_token', 'retire', 'abort',
                          'quota_defer', 'deadline_miss',
                          'degrade_stage'):
                    spans.append({
                        'name': f'{tr.req_id}:{ev}',
                        'cat': 'serve_request', 'ts': t_us, 'dur': 0,
                        'tid': tid, 'tname': tname,
                        'pid': _TRACK_PID, 'pname': _TRACK_PNAME,
                        'args': {k: v for k, v in e.items()
                                 if k not in ('t',)}})
        return spans

    def export_chrome_tracing(self, path, extra_spans=None):
        """Chrome-trace export through the profiler's writer; pass the
        profiler span buffer (engine serve::* phases) as `extra_spans`
        to see requests as tracks next to engine steps."""
        from .. import profiler as _prof
        spans = self.chrome_spans() + list(extra_spans or ())
        return _prof._write_chrome_trace(
            path, spans, metadata={'schema': SCHEMA})


# ---------------------------------------------------------------------------
# reconstruction — trace events -> per-request SLO table
# ---------------------------------------------------------------------------
def reconstruct(events):
    """Derive the per-request lifecycle summary from a flat event list
    (live tracer or a loaded JSON-lines file). Returns {req_id: {...}}
    with queue_wait_s / ttft_s / tpot_s / e2e_s, token counts,
    preemptions, prefill chunks, decode steps, pages high-water —
    exactly the numbers the engine reports, re-derived from the journal
    (the equivalence is asserted in tests)."""
    out = {}
    for e in sorted(events, key=lambda x: x['t']):
        if isinstance(e['req'], int) and e['req'] < 0:
            continue        # engine-scope event (degrade_stage) — not
                            # a request lifecycle; see ENGINE_REQ
        r = out.setdefault(e['req'], {
            'req': e['req'], 'submit_t': None, 'admit_t': None,
            'first_token_t': None, 'end_t': None, 'state': None,
            'prompt_tokens': None, 'tokens_generated': 0,
            'preemptions': 0, 'prefill_chunks': 0, 'decode_steps': 0,
            'pages_high_water': 0, 'last_token_t': None,
            'prefix_cached_tokens': 0, 'spec_proposed': 0,
            'spec_accepted': 0, 'replica_id': None,
            'router_decision': None,
            # schema v3 tenancy/degradation columns (ISSUE 15): v1/v2
            # traces simply leave the defaults
            'tenant_id': None, 'priority': 0, 'deadline_s': None,
            'quota_defers': 0, 'deadline_miss': False,
            # schema v4 goodput pricing (ISSUE 17): computed prefill
            # positions, preempt-destroyed recompute, and the verify
            # columns that never reached the request — older traces
            # leave zeros and the derived columns degrade gracefully
            'prefill_tokens_computed': 0, 'recompute_tokens': 0,
            'spec_discarded': 0, 'prefill_samples': 0,
            # schema v5 fused decode (ISSUE 19): windows this request
            # rode and tokens it took from them — older traces leave
            # zeros (no fused engine existed to emit them)
            'fused_windows': 0, 'fused_tokens': 0,
            # schema v6 host tier (ISSUE 20): prefix pages this
            # request resurrected from host RAM instead of
            # re-prefilling, and the prompt tokens those pages carried
            # — older traces leave zeros (no tier existed)
            'resurrected_pages': 0, 'resurrected_tokens': 0,
        })
        ev, t = e['event'], e['t']
        # `pages` on a resurrect event counts pages FETCHED, not the
        # request's page-table size — keep it out of the high-water
        if 'pages' in e and e['event'] != 'resurrect':
            r['pages_high_water'] = max(r['pages_high_water'],
                                        int(e['pages']))
        if ev == 'submit':
            r['submit_t'] = t
            r['prompt_tokens'] = e.get('prompt_tokens')
            r['tenant_id'] = e.get('tenant_id')
            r['priority'] = e.get('priority', 0)
            r['deadline_s'] = e.get('deadline_s')
        elif ev == 'route':
            # schema v2: which replica got this request and why; the
            # FIRST placement wins (a drain-resubmit lands in the
            # peer's own trace file under a new request id)
            if r['replica_id'] is None:
                r['replica_id'] = e.get('replica_id')
                r['router_decision'] = e.get('router_decision')
        elif ev == 'admit' and r['admit_t'] is None:
            r['admit_t'] = t
        elif ev == 'resume':
            pass                         # re-admit after preempt
        elif ev == 'prefix_hit':
            # one hit per (re-)prefill start; resumes can hit again on
            # their own released pages, so cached tokens accumulate
            r['prefix_cached_tokens'] += int(e.get('cached_tokens', 0))
        elif ev == 'spec_verify':
            r['spec_proposed'] += int(e.get('proposed', 0))
            r['spec_accepted'] += int(e.get('accepted', 0))
            # v4: accepted-but-dropped burst tail (eos/budget) — with
            # the rejected drafts, the request's spec waste
            r['spec_discarded'] += int(e.get('discarded', 0))
        elif ev == 'prefill_chunk':
            r['prefill_chunks'] += 1
            r['prefill_tokens_computed'] += int(e.get('tokens', 0))
            r['recompute_tokens'] += int(e.get('recompute_tokens', 0))
            r['prefill_samples'] += int(e.get('sampled', 0))
        elif ev == 'first_token':
            r['first_token_t'] = t
            r['tokens_generated'] = max(r['tokens_generated'],
                                        e.get('tokens_generated', 1))
            r['last_token_t'] = t
        elif ev == 'decode':
            r['decode_steps'] += 1
            r['tokens_generated'] = max(r['tokens_generated'],
                                        e.get('tokens_generated',
                                              r['tokens_generated'] + 1))
            r['last_token_t'] = t
        elif ev == 'fused_decode':
            # v5: one event per fused window; `accepted` tokens each
            # stand in for one serial decode step, so the derived
            # decode_steps/TPOT columns stay comparable across
            # fused and serial traces
            acc = int(e.get('accepted', 1))
            r['decode_steps'] += acc
            r['fused_windows'] += 1
            r['fused_tokens'] += acc
            r['tokens_generated'] = max(r['tokens_generated'],
                                        e.get('tokens_generated',
                                              r['tokens_generated']
                                              + acc))
            r['last_token_t'] = t
        elif ev == 'resurrect':
            # v6: prefix pages fetched back from the host tier at
            # prefill start — compute the resurrect replaced
            r['resurrected_pages'] += int(e.get('pages', 0))
            r['resurrected_tokens'] += int(e.get('tokens', 0))
        elif ev == 'quota_defer':
            r['quota_defers'] += 1
        elif ev == 'deadline_miss':
            r['deadline_miss'] = True
        elif ev == 'preempt':
            r['preemptions'] += 1
        elif ev in ('retire', 'abort'):
            r['end_t'] = t
            r['state'] = 'aborted' if ev == 'abort' else 'finished'
            if 'tokens_generated' in e:
                r['tokens_generated'] = e['tokens_generated']
    for r in out.values():
        sub, adm = r['submit_t'], r['admit_t']
        ft, end = r['first_token_t'], r['end_t']
        last = r.pop('last_token_t')
        n = r['tokens_generated']
        r['queue_wait_s'] = (adm - sub) if sub is not None \
            and adm is not None else None
        r['ttft_s'] = (ft - sub) if sub is not None \
            and ft is not None else None
        # the terminal stamp closes the last token interval — the SAME
        # formula engine._observe_slo feeds the TPOT histogram, so the
        # journal-derived value matches the engine's exactly; fall back
        # to the last decode stamp for still-live requests
        stop = end if end is not None else last
        r['tpot_s'] = ((stop - ft) / (n - 1)) if ft is not None \
            and stop is not None and n > 1 else None
        r['e2e_s'] = (end - sub) if sub is not None \
            and end is not None else None
        # v4 goodput columns: delivered = first-time prefill positions
        # + appended decode tokens. Every COMPLETED prefill (the
        # initial one and each post-preemption resume) samples a token
        # off its final column — v4 marks those chunks `sampled`, so
        # the decode share is n minus all of them; pre-v4 journals only
        # know about the first token. Wasted = preempt recompute + spec
        # columns that never landed. Matches the engine ledger's
        # per-request charges exactly on a v4 trace; v1-v3 leave the
        # prefill/spec fields zero and price what the journal knows.
        decode_delivered = max(
            n - max(r['prefill_samples'],
                    1 if ft is not None else 0), 0)
        r['delivered_tokens'] = (
            max(r['prefill_tokens_computed'] - r['recompute_tokens'], 0)
            + decode_delivered)
        r['wasted_tokens'] = (
            r['recompute_tokens']
            + max(r['spec_proposed'] - r['spec_accepted'], 0)
            + r['spec_discarded'])
    return out


def percentile_of(vals, q):
    """Linear-interpolated percentile of a value list (None entries
    dropped; None when nothing remains). The one implementation both
    bench.py and tools/trace_summary.py aggregate request tables with."""
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    pos = q / 100.0 * (len(vals) - 1)
    i = int(pos)
    frac = pos - i
    hi = vals[min(i + 1, len(vals) - 1)]
    return vals[i] * (1 - frac) + hi * frac


def load_trace(path):
    """Read an export_jsonl file back into (header, events). All
    three schema versions load — v1 traces carry no route events (so
    reconstruct() leaves replica_id/router_decision at None), v1/v2
    carry no tenancy/degradation events (tenant columns default). An
    unknown serve_trace version raises rather than silently
    mis-reading a future layout."""
    header, events = {}, []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if 'schema' in doc and 'event' not in doc:
                schema = doc.get('schema', '')
                if schema.startswith('paddle_tpu.serve_trace/') \
                        and schema not in SCHEMAS:
                    raise ValueError(
                        f"unsupported serve trace schema {schema!r} "
                        f"(this build reads {SCHEMAS})")
                header = doc
            elif 'event' in doc and 'req' in doc:
                events.append(doc)
    return header, events


# ---------------------------------------------------------------------------
# stalled-request watchdog artifact (serve_report)
# ---------------------------------------------------------------------------
def build_serve_report(reason, request_summary, trace_events,
                       timeline_tail, pool_stats, pool_census,
                       engine_stats=None):
    """Structured serve_report dict — the serving pillar's counterpart
    of the PR-2 hang/OOM reports (kind-tagged, health_dump-renderable)."""
    return {
        'kind': 'serve_report',
        'schema': SCHEMA,
        'reason': reason,
        'request': request_summary,
        'trace': list(trace_events),
        'timeline_tail': list(timeline_tail),
        'pool': dict(pool_stats or {}),
        'pool_census': dict(pool_census or {}),
        'engine': dict(engine_stats or {}),
    }


def write_serve_report(report, report_dir=None):
    """Persist a serve_report; directory resolution follows the PR-2
    artifact conventions (explicit dir > PTPU_SERVE_REPORT_DIR >
    FLEET_LOG_DIR > cwd). Also emits a structured log_util event so the
    fleet log cross-references the artifact. Returns the path (None if
    the write failed — the report still reached the log)."""
    d = (report_dir or os.environ.get('PTPU_SERVE_REPORT_DIR')
         or os.environ.get('FLEET_LOG_DIR'))
    req = report.get('request') or {}
    path = None
    if d:       # no dir configured -> artifact stays on the engine
                # (last_serve_report) and in the structured log only
        path = os.path.join(d,
                            f"serve_report.req{req.get('req', 'X')}.json")
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, 'w') as f:
                json.dump(report, f, indent=1)
        except OSError:
            path = None
    try:
        from ..distributed.fleet.utils.log_util import log_json
        log_json('serve_request_stalled', level='error',
                 msg=f"serving request {req.get('req')} exceeded its "
                     f"deadline ({report.get('reason')})",
                 request=req.get('req'), state=req.get('state'),
                 age_s=req.get('age_s'), deadline_s=req.get('deadline_s'),
                 report_path=path)
    except Exception:
        pass
    return path


def render_serve_report(doc):
    """Human rendering of a serve_report artifact (health_dump.py)."""
    req = doc.get('request') or {}
    out = [f"SERVE REPORT — {doc.get('reason', '?')}"]
    out.append(
        f"  request {req.get('req')}: state={req.get('state')} "
        f"age={_ms(req.get('age_s'))} deadline={_ms(req.get('deadline_s'))}")
    out.append(
        f"  prompt {req.get('prompt_tokens')} tokens, "
        f"{req.get('tokens_generated', 0)} generated, "
        f"{req.get('preemptions', 0)} preemptions")
    table = reconstruct(doc.get('trace') or [])
    r = table.get(req.get('req'))
    if r:
        out.append(
            f"  queue-wait {_ms(r['queue_wait_s'])}  "
            f"ttft {_ms(r['ttft_s'])}  tpot {_ms(r['tpot_s'])}  "
            f"pages high-water {r['pages_high_water']}")
    evs = doc.get('trace') or []
    out.append(f"  trace tail ({len(evs)} events):")
    for e in evs[-8:]:
        extra = ' '.join(f'{k}={v}' for k, v in e.items()
                         if k not in ('req', 'event', 't'))
        out.append(f"    t={e['t']:.6f} {e['event']}"
                   + (f' {extra}' if extra else ''))
    tl = doc.get('timeline_tail') or []
    if tl:
        out.append(f"  scheduler timeline tail ({len(tl)} iterations):")
        for it in tl[-5:]:
            out.append(
                f"    iter {it.get('iter')}: "
                f"slots {it.get('decode_slots_occupied')}/"
                f"{it.get('decode_slots')} "
                f"prefill {it.get('prefill_tokens')}t "
                f"decode {it.get('decode_tokens')}t "
                f"pool {it.get('pool_pages_in_use')}/"
                f"{it.get('pool_pages_total')} "
                f"waiting {it.get('waiting')} "
                f"admit {it.get('admissions')} "
                f"preempt {it.get('preemptions')}")
    pool = doc.get('pool') or {}
    out.append(
        f"  pool: {pool.get('pages_in_use')}/{pool.get('num_pages')} "
        f"pages in use, high water {pool.get('high_water')}")
    census = doc.get('pool_census') or {}
    if census:
        rows = ', '.join(f'req {k}: {v} pages'
                         for k, v in sorted(census.items(),
                                            key=lambda kv: -kv[1])[:8])
        out.append(f"  pool census: {rows}")
    return '\n'.join(out)


def _ms(v):
    return f'{v * 1000.0:.1f}ms' if isinstance(v, (int, float)) else '?'
