"""Serving goodput ledger & decode roofline (ISSUE 17) — the serving
twin of the core step-time ledger (core/ledger.py, ISSUE 16).

Three accounts per engine site:

1. **ServeLedger wall decomposition** — every engine iteration's wall
   splits into compute / sampled-token host fetch / scheduling
   (admit+retire+preempt sweep) / page-stream (disagg handoffs) /
   residue under the PR-16 ordered-clamp discipline: each measured
   component is clamped to the wall remaining after the ones before
   it, residue is the remainder (surfaced, never hidden), and
   `reconciled_fraction` == sum(components)/wall flags any overrun
   instead of silently eating it. The engine's host syncs run through
   a registered `core.async_step.HostGapMonitor` (site 'serve'), so
   serving publishes a real `host_bound_fraction`: the fraction of the
   step interval the host spends blocked on the sampled-token fetch.

2. **Goodput ledger** — emitted tokens (every token position the
   compiled steps actually computed: chunked-prefill positions plus
   decode/verify query rows) split into delivered vs wasted:

     * preempt_recompute — positions re-prefilled after a preemption
       destroyed their KV (priced at recompute time from the
       request's computed high-water mark, so prefix-cache
       resurrection correctly shrinks the bill);
     * spec_rejected    — verify columns computed but never appended
       (rejected drafts, plus the post-eos overdraft of a burst);
     * drain_recompute  — cluster-level only: the router prices the
       prefix a drain-resubmit makes a peer re-prefill
       (`ptpu_route_drain_recompute_tokens_total`) and
       `cluster_snapshot()` moves it from delivered to wasted.

   The identity `delivered + wasted == emitted` holds exactly by
   construction at every level. Degrade-shed speculative capacity
   (`spec_shed_tokens`) is priced separately: those tokens were never
   computed, so they sit OUTSIDE the identity as foregone capacity,
   not inside `wasted`.

3. **Decode roofline** — decode is bandwidth-bound, so its roofline is
   bytes moved per iteration: resident param bytes (at the serving
   weight dtype, int8 q+scale buffers included) plus KV page reads at
   the pool's `bytes_per_token()` over the active requests' context
   lengths. Achieved GB/s over the compiled-step wall against a
   per-TPU-generation HBM peak table gives MBU; prefill chunks reuse
   the PR-16 analytic FLOPs (forward share) for a prefill MFU. On
   CPU/unknown devices both utilizations are None — absolute GB/s and
   TFLOP/s only, never a faked percentage.

Everything lands as `ptpu_serve_ledger_*` / `ptpu_serve_goodput_*`
gauges (labeled by engine site) and flows into
`StepTelemetry.snapshot()['serve']` via `metrics.serve_snapshot()`,
replica `status()`, and the router's `cluster_snapshot()`.
Engines register here at build and `unregister()` at shutdown so dead
engines stop reporting (the PR-13 training-engine discipline).
"""
import collections
import threading

__all__ = ['ServeLedger', 'serve_ledger_snapshot', 'render_serve_ledger',
           'resolve_peak_hbm_gbps', 'HBM_GBPS', 'unregister_ledger']


# ---------------------------------------------------------------------------
# per-device HBM bandwidth peak table (GB/s per chip, by TPU generation
# — docs/observability.md#serving-ledger). The MBU denominator, exactly
# as PEAK_TFLOPS_BF16 is the MFU one.
# ---------------------------------------------------------------------------
HBM_GBPS = (
    ('v6', 1638.0),         # Trillium
    ('trillium', 1638.0),
    ('v5p', 2765.0),
    ('v5 lite', 819.0),     # device_kind 'TPU v5 lite'
    ('v5litepod', 819.0),
    ('v5e', 819.0),
    ('v4', 1228.0),
    ('v3', 900.0),
    ('v2', 700.0),
)


def resolve_peak_hbm_gbps(device_kind=None):
    """Per-chip HBM bandwidth peak for the local accelerator, or None
    when it is not a TPU (CPU dryrun: absolute GB/s only, no MBU)."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    k = str(device_kind).lower()
    if 'tpu' not in k and 'trillium' not in k:
        return None
    for sub, peak in HBM_GBPS:
        if sub in k:
            return peak
    return None


# engine site -> ServeLedger (latest per site wins — the monitor
# registry convention). serve_ledger_snapshot() reads LIVE ledgers, so
# an engine that unregistered at shutdown stops reporting immediately.
_ledgers = {}
_ledgers_lock = threading.Lock()


def unregister_ledger(ledger):
    """Drop a ledger from the snapshot registry if it is still the
    registered one for its site (a newer engine's ledger wins)."""
    with _ledgers_lock:
        if _ledgers.get(ledger.engine) is ledger:
            del _ledgers[ledger.engine]


_WASTE_CAUSES = ('preempt_recompute', 'spec_rejected', 'drain_recompute')
_COMPONENTS = ('compute', 'host_fetch', 'schedule', 'page_stream',
               'residue')


class ServeLedger:
    """Per-engine serving account. The engine constructs one beside its
    HostGapMonitor, feeds it per-iteration phase timings
    (`observe_iteration`) and per-token classifications
    (`account_prefill` / `account_decode` / `account_spec_shed`) from
    the step hot path — pure host floats on data the engine already
    holds, zero device syncs — and `publish()`es from
    `publish_metrics()`."""

    def __init__(self, engine='serve', gap=None, window=256,
                 n_params=0, layers=0, hidden=0, param_bytes=0,
                 kv_bytes_per_token=0, peak_hbm_gbps=None,
                 peak_tflops=None):
        self.engine = engine
        self._gap = gap
        self.n_params = int(n_params)
        self.layers = int(layers)
        self.hidden = int(hidden)
        self.param_bytes = int(param_bytes)
        self.kv_bytes_per_token = int(kv_bytes_per_token)
        self._peak_hbm = peak_hbm_gbps
        self._peak_tflops = peak_tflops
        self._window = int(window)
        # per-iteration rolling samples (seconds / counts)
        self._walls = collections.deque(maxlen=window)
        self._compute = collections.deque(maxlen=window)
        self._fetch = collections.deque(maxlen=window)
        self._schedule = collections.deque(maxlen=window)
        self._stream = collections.deque(maxlen=window)
        # decode-roofline samples (decode iterations only)
        self._decode_s = collections.deque(maxlen=window)
        self._kv_tokens = collections.deque(maxlen=window)
        # prefill-roofline samples (prefill dispatches only)
        self._prefill_s = collections.deque(maxlen=window)
        self._prefill_tok = collections.deque(maxlen=window)
        self._prefill_ctx = collections.deque(maxlen=window)
        self._pending_stream = 0.0      # disagg handoff seconds noted
                                        # between iterations
        self.iterations = 0
        # goodput counters (lifetime, host ints)
        self.emitted_tokens = 0
        self.delivered_tokens = 0
        self.wasted = {c: 0 for c in _WASTE_CAUSES}
        self.spec_shed_tokens = 0
        # fused decode windows (ISSUE 19): dispatches, the device
        # iterations they ran, and the tokens they delivered — the
        # per-window host-fetch attribution's denominator
        self.fused_windows = 0
        self.fused_iterations = 0
        self.fused_tokens = 0
        self._per_tenant = {}
        with _ledgers_lock:
            _ledgers[engine] = self

    # -- hot path: wall decomposition ---------------------------------------
    def note_page_stream(self, seconds):
        """A disagg prefill→decode page handoff just spent `seconds`
        streaming pages — folded into the NEXT observed iteration's
        page_stream component (the facade streams between the two
        engines' step sweeps)."""
        self._pending_stream += max(float(seconds), 0.0)

    def observe_iteration(self, wall, compute=0.0, host_fetch=0.0,
                          schedule=0.0, decode_seconds=0.0,
                          kv_read_tokens=0, prefill_tokens=0,
                          prefill_seconds=0.0, prefill_ctx_tokens=0):
        """One engine iteration's measured phase walls (host
        perf_counter segments — no device syncs)."""
        self.iterations += 1
        self._walls.append(max(float(wall), 0.0))
        self._compute.append(max(float(compute), 0.0))
        self._fetch.append(max(float(host_fetch), 0.0))
        self._schedule.append(max(float(schedule), 0.0))
        self._stream.append(self._pending_stream)
        self._pending_stream = 0.0
        if decode_seconds > 0.0:
            self._decode_s.append(float(decode_seconds))
            self._kv_tokens.append(int(kv_read_tokens))
        if prefill_tokens > 0:
            self._prefill_s.append(max(float(prefill_seconds), 0.0))
            self._prefill_tok.append(int(prefill_tokens))
            self._prefill_ctx.append(int(prefill_ctx_tokens))

    # -- hot path: goodput --------------------------------------------------
    def _tenant_row(self, tenant_id):
        tid = str(tenant_id)
        row = self._per_tenant.get(tid)
        if row is None:
            row = self._per_tenant[tid] = {'delivered_tokens': 0,
                                           'wasted_tokens': 0}
        return row

    def account_prefill(self, first_time, recompute, tenant_id=None):
        """One prefill chunk's computed positions: `first_time` never
        computed before (delivered prompt work), `recompute` positions
        a preemption destroyed and this chunk re-derives (wasted)."""
        ft, rc = max(int(first_time), 0), max(int(recompute), 0)
        self.emitted_tokens += ft + rc
        self.delivered_tokens += ft
        self.wasted['preempt_recompute'] += rc
        if tenant_id is not None and (ft or rc):
            row = self._tenant_row(tenant_id)
            row['delivered_tokens'] += ft
            row['wasted_tokens'] += rc
    def account_decode(self, delivered, rejected, tenant_id=None):
        """One request's decode/verify row: `delivered` tokens appended
        to the request, `rejected` query columns computed but discarded
        (failed draft verification or post-eos overdraft)."""
        d, rj = max(int(delivered), 0), max(int(rejected), 0)
        self.emitted_tokens += d + rj
        self.delivered_tokens += d
        self.wasted['spec_rejected'] += rj
        if tenant_id is not None and (d or rj):
            row = self._tenant_row(tenant_id)
            row['delivered_tokens'] += d
            row['wasted_tokens'] += rj

    def account_fused_window(self, k, iterations, tokens):
        """One fused decode window: configured window length `k`, the
        `iterations` the scan actually advanced anyone (<= k when every
        row went done early), and the tokens it delivered. The window's
        single host fetch is already amortized across its iterations by
        the engine's observe_iteration calls; these counters carry the
        window shape itself (gauges + health_dump)."""
        self.fused_windows += 1
        self.fused_iterations += max(int(iterations), 0)
        self.fused_tokens += max(int(tokens), 0)

    def account_spec_shed(self, tokens, tenant_id=None):
        """Draft capacity the degradation ladder shed this decode step
        (stage >= 1 with spec configured on): foregone tokens that were
        never computed — OUTSIDE the delivered+wasted==emitted
        identity, reported as shed capacity."""
        self.spec_shed_tokens += max(int(tokens), 0)

    # -- accounts ------------------------------------------------------------
    @staticmethod
    def _mean(dq):
        return (sum(dq) / len(dq)) if dq else 0.0

    def account(self):
        """The reconciled per-iteration wall decomposition, or None
        before the first observed iteration. Ordered clamps (PR-16):
        compute, then host_fetch, then schedule, then page_stream each
        clamp to the wall remaining before them; residue is the
        remainder. `measured` carries the raw means so a clamp that
        bit is visible, and reconciled_fraction > 1 flags measured
        components exceeding the wall."""
        if not self._walls:
            return None
        wall = self._mean(self._walls)
        if wall <= 0.0:
            return None
        m_compute = self._mean(self._compute)
        m_fetch = self._mean(self._fetch)
        m_sched = self._mean(self._schedule)
        m_stream = self._mean(self._stream)
        compute = min(m_compute, wall)
        fetch = min(m_fetch, max(wall - compute, 0.0))
        sched = min(m_sched, max(wall - compute - fetch, 0.0))
        stream = min(m_stream, max(wall - compute - fetch - sched, 0.0))
        residue = max(wall - compute - fetch - sched - stream, 0.0)
        total = compute + fetch + sched + stream + residue
        overrun = m_compute + m_fetch + m_sched + m_stream
        snap = self._gap.snapshot() if self._gap is not None else {}
        return {
            'engine': self.engine,
            'iterations': self.iterations,
            'wall_seconds': wall,
            'components': {
                'compute': compute,
                'host_fetch': fetch,
                'schedule': sched,
                'page_stream': stream,
                'residue': residue,
            },
            'measured': {
                'compute': m_compute, 'host_fetch': m_fetch,
                'schedule': m_sched, 'page_stream': m_stream,
            },
            'reconciled_fraction':
                (max(total, overrun) / wall) if wall else 0.0,
            'host_bound_fraction': snap.get('host_bound_fraction'),
            'host_gap_seconds': snap.get('host_gap_seconds'),
            'fused_windows': self.fused_windows,
            'fused_iterations': self.fused_iterations,
            'fused_tokens': self.fused_tokens,
        }

    def goodput(self):
        """The goodput account: delivered + wasted == emitted exactly
        (wasted = the three computed-token causes; spec_shed is
        foregone capacity, reported beside the identity)."""
        wasted_total = sum(self.wasted.values())
        emitted = self.emitted_tokens
        return {
            'engine': self.engine,
            'emitted_tokens': emitted,
            'delivered_tokens': self.delivered_tokens,
            'wasted_tokens': wasted_total,
            'wasted_by_cause': dict(self.wasted),
            'spec_shed_tokens': self.spec_shed_tokens,
            'goodput_fraction':
                (self.delivered_tokens / emitted) if emitted else None,
            'per_tenant': {t: dict(r)
                           for t, r in self._per_tenant.items()},
        }

    def roofline(self):
        """The decode bytes-moved roofline + prefill FLOPs roofline, or
        None before any decode/prefill dispatch was observed. MBU/MFU
        are None off-TPU — absolute GB/s / TFLOP/s only."""
        out = None
        if self._decode_s:
            dt = self._mean(self._decode_s)
            kv_tokens = self._mean(self._kv_tokens)
            bytes_per_iter = (self.param_bytes
                              + kv_tokens * self.kv_bytes_per_token)
            gbps = (bytes_per_iter / dt / 1e9) if dt > 0.0 else 0.0
            peak = (self._peak_hbm if self._peak_hbm is not None
                    else resolve_peak_hbm_gbps())
            out = {
                'engine': self.engine,
                'decode_bytes_per_iteration': bytes_per_iter,
                'param_bytes': self.param_bytes,
                'kv_read_tokens_mean': kv_tokens,
                'kv_bytes_per_token': self.kv_bytes_per_token,
                'decode_seconds_mean': dt,
                'hbm_gbps': gbps,
                'peak_hbm_gbps': peak,
                'mbu': (gbps / peak) if (peak and gbps) else None,
            }
        if self._prefill_s and sum(self._prefill_s) > 0.0 \
                and self.n_params:
            from ..core.ledger import (model_flops_per_step,
                                       resolve_peak_tflops)
            tokens = sum(self._prefill_tok)
            ctx = sum(self._prefill_ctx)
            secs = sum(self._prefill_s)
            # forward share of the fwd+bwd analytic count (6NT + 12LHST
            # is 1 fwd + 2 bwd passes): inference runs the forward only.
            # The attention term's seq_len is the token-weighted mean
            # context each chunk attended over.
            seq_eff = (ctx / tokens) if tokens else 0
            total, _attn = model_flops_per_step(
                self.n_params, tokens, layers=self.layers,
                hidden=self.hidden, seq_len=seq_eff)
            fwd = total / 3.0
            tflops = fwd / secs / 1e12 if secs else 0.0
            peak_t = (self._peak_tflops if self._peak_tflops is not None
                      else resolve_peak_tflops())
            out = dict(out or {'engine': self.engine})
            out.update({
                'prefill_tokens': int(tokens),
                'prefill_seconds': secs,
                'prefill_model_flops': fwd,
                'prefill_tflops': tflops,
                'peak_tflops': peak_t,
                'prefill_mfu':
                    (tflops / peak_t) if (peak_t and tflops) else None,
            })
        return out

    # -- lifecycle -----------------------------------------------------------
    def reset(self):
        """Zero the rolling windows and goodput counters (bench warmup
        boundary — rides engine.reset_stats())."""
        for dq in (self._walls, self._compute, self._fetch,
                   self._schedule, self._stream, self._decode_s,
                   self._kv_tokens, self._prefill_s, self._prefill_tok,
                   self._prefill_ctx):
            dq.clear()
        self._pending_stream = 0.0
        self.iterations = 0
        self.emitted_tokens = 0
        self.delivered_tokens = 0
        self.wasted = {c: 0 for c in _WASTE_CAUSES}
        self.spec_shed_tokens = 0
        self.fused_windows = 0
        self.fused_iterations = 0
        self.fused_tokens = 0
        self._per_tenant = {}

    def unregister(self):
        unregister_ledger(self)

    # -- publication (publish_metrics cadence, never per token) -------------
    def publish(self):
        acct = self.account()
        good = self.goodput()
        roof = self.roofline()
        try:
            from ..core import monitor as _m
            e = self.engine
            if acct is not None:
                _m.gauge('ptpu_serve_ledger_wall_seconds',
                         help='serving ledger: mean engine-iteration '
                              'wall',
                         labelnames=('engine',)).set(
                             acct['wall_seconds'], engine=e)
                comp = _m.gauge(
                    'ptpu_serve_ledger_component_seconds',
                    help='serving ledger: per-iteration seconds per '
                         'component (compute/host_fetch/schedule/'
                         'page_stream/residue)',
                    labelnames=('engine', 'component'))
                for name, v in acct['components'].items():
                    comp.set(v, engine=e, component=name)
                _m.gauge('ptpu_serve_ledger_reconciled_fraction',
                         help='serving ledger: sum(components)/wall '
                              '(1.0 = reconciled; >1 flags measured '
                              'components exceeding the wall)',
                         labelnames=('engine',)).set(
                             acct['reconciled_fraction'], engine=e)
                if acct['host_bound_fraction'] is not None:
                    _m.gauge(
                        'ptpu_serve_ledger_host_bound_fraction',
                        help='serving: fraction of the step interval '
                             'the host spends blocked on the sampled-'
                             'token fetch (HostGapMonitor gating)',
                        labelnames=('engine',)).set(
                            acct['host_bound_fraction'], engine=e)
                _m.gauge('ptpu_serve_ledger_fused_windows_total',
                         help='fused decode: k-iteration windows '
                              'dispatched (one host fetch each)',
                         labelnames=('engine',)).set(
                             acct['fused_windows'], engine=e)
                _m.gauge('ptpu_serve_ledger_fused_iterations_total',
                         help='fused decode: device iterations run '
                              'inside fused windows (each the '
                              'equivalent of one serial decode step)',
                         labelnames=('engine',)).set(
                             acct['fused_iterations'], engine=e)
            _m.gauge('ptpu_serve_goodput_emitted_tokens',
                     help='goodput: token positions the compiled steps '
                          'computed (lifetime)',
                     labelnames=('engine',)).set(good['emitted_tokens'],
                                                 engine=e)
            _m.gauge('ptpu_serve_goodput_delivered_tokens',
                     help='goodput: emitted tokens that reached a '
                          'request as useful work (lifetime)',
                     labelnames=('engine',)).set(
                         good['delivered_tokens'], engine=e)
            wg = _m.gauge(
                'ptpu_serve_goodput_wasted_tokens',
                help='goodput: emitted tokens destroyed or discarded, '
                     'by cause (preempt_recompute/spec_rejected/'
                     'drain_recompute)',
                labelnames=('engine', 'cause'))
            for cause, v in good['wasted_by_cause'].items():
                wg.set(v, engine=e, cause=cause)
            _m.gauge('ptpu_serve_goodput_spec_shed_tokens',
                     help='goodput: draft capacity the degradation '
                          'ladder shed (never computed — outside the '
                          'delivered+wasted identity)',
                     labelnames=('engine',)).set(
                         good['spec_shed_tokens'], engine=e)
            if good['goodput_fraction'] is not None:
                _m.gauge('ptpu_serve_goodput_fraction',
                         help='goodput: delivered / emitted tokens',
                         labelnames=('engine',)).set(
                             good['goodput_fraction'], engine=e)
            if roof is not None and 'hbm_gbps' in roof:
                _m.gauge('ptpu_serve_ledger_bytes_per_iteration',
                         help='decode roofline: modeled bytes moved '
                              'per decode iteration (params + KV page '
                              'reads)',
                         labelnames=('engine',)).set(
                             roof['decode_bytes_per_iteration'],
                             engine=e)
                _m.gauge('ptpu_serve_ledger_hbm_gbps',
                         help='decode roofline: achieved HBM GB/s '
                              '(modeled bytes / measured compiled-'
                              'step wall)',
                         labelnames=('engine',)).set(roof['hbm_gbps'],
                                                     engine=e)
                if roof.get('peak_hbm_gbps'):
                    _m.gauge('ptpu_serve_ledger_peak_hbm_gbps',
                             help='decode roofline: per-chip HBM '
                                  'bandwidth peak for the local TPU '
                                  'generation',
                             labelnames=('engine',)).set(
                                 roof['peak_hbm_gbps'], engine=e)
                if roof.get('mbu') is not None:
                    _m.gauge('ptpu_serve_ledger_mbu',
                             help='decode roofline: memory-bandwidth '
                                  'utilization vs the per-chip peak '
                                  '(absent on CPU dryruns)',
                             labelnames=('engine',)).set(roof['mbu'],
                                                         engine=e)
            if roof is not None and 'prefill_tflops' in roof:
                _m.gauge('ptpu_serve_ledger_prefill_tflops',
                         help='prefill roofline: achieved forward '
                              'model TFLOP/s over prefill dispatches',
                         labelnames=('engine',)).set(
                             roof['prefill_tflops'], engine=e)
                if roof.get('prefill_mfu') is not None:
                    _m.gauge('ptpu_serve_ledger_prefill_mfu',
                             help='prefill roofline: model-FLOPs '
                                  'utilization vs the per-chip peak '
                                  '(absent on CPU dryruns)',
                             labelnames=('engine',)).set(
                                 roof['prefill_mfu'], engine=e)
        except Exception:
            pass
        return acct


def serve_ledger_snapshot():
    """The live ledger registry's JSON-ready view, or None when no
    serving ledger is registered (every engine shut down). Shape:

      {'ledger':   {site: account()},        # may be all-None values
       'goodput':  merged goodput across sites (one pipeline),
       'roofline': {site: roofline()}}

    Goodput merges across sites because a disaggregated pipeline's
    prefill and decode engines split one token stream; the ledger and
    roofline stay per site (their walls are different loops).
    """
    with _ledgers_lock:
        ledgers = dict(_ledgers)
    if not ledgers:
        return None
    ledger = {}
    roofline = {}
    merged = {'emitted_tokens': 0, 'delivered_tokens': 0,
              'wasted_tokens': 0,
              'wasted_by_cause': {c: 0 for c in _WASTE_CAUSES},
              'spec_shed_tokens': 0, 'per_tenant': {}}
    for site, led in sorted(ledgers.items()):
        acct = led.account()
        if acct is not None:
            ledger[site] = acct
        roof = led.roofline()
        if roof is not None:
            roofline[site] = roof
        g = led.goodput()
        for k in ('emitted_tokens', 'delivered_tokens', 'wasted_tokens',
                  'spec_shed_tokens'):
            merged[k] += g[k]
        for c, v in g['wasted_by_cause'].items():
            merged['wasted_by_cause'][c] = \
                merged['wasted_by_cause'].get(c, 0) + v
        for tid, row in g['per_tenant'].items():
            dst = merged['per_tenant'].setdefault(
                tid, {'delivered_tokens': 0, 'wasted_tokens': 0})
            dst['delivered_tokens'] += row['delivered_tokens']
            dst['wasted_tokens'] += row['wasted_tokens']
    merged['goodput_fraction'] = (
        merged['delivered_tokens'] / merged['emitted_tokens']
        if merged['emitted_tokens'] else None)
    return {'ledger': ledger or None,
            'goodput': merged,
            'roofline': roofline or None}


def render_serve_ledger(snap):
    """Human rendering of a serve_ledger_snapshot() dict (shared with
    tools/health_dump.py serve)."""
    out = ['== serving ledger ' + '=' * 42]
    for site, a in sorted((snap.get('ledger') or {}).items()):
        wall = a.get('wall_seconds') or 0.0
        hbf = a.get('host_bound_fraction')
        out.append(
            f"engine: {site}   wall {wall * 1e3:.3f} ms/iter   "
            f"reconciled {(a.get('reconciled_fraction') or 0):.3f}"
            + (f"   host-bound {hbf * 100:.1f}%"
               if hbf is not None else ''))
        comps = a.get('components') or {}
        for name in _COMPONENTS:
            v = comps.get(name) or 0.0
            pct = (v / wall * 100.0) if wall else 0.0
            out.append(f"  {name:<12} {v * 1e3:>10.3f} ms  {pct:5.1f}%")
        fw = a.get('fused_windows') or 0
        if fw:
            fi = a.get('fused_iterations') or 0
            out.append(
                f"  fused decode: {fi} iterations in {fw} windows "
                f"(mean k {fi / fw:.1f}), "
                f"{a.get('fused_tokens') or 0} tokens, one host fetch "
                f"per window")
    g = snap.get('goodput') or {}
    if g:
        frac = g.get('goodput_fraction')
        out.append(
            f"goodput: {g.get('delivered_tokens', 0)} delivered / "
            f"{g.get('wasted_tokens', 0)} wasted of "
            f"{g.get('emitted_tokens', 0)} emitted"
            + (f"  ({frac * 100:.1f}% goodput)"
               if frac is not None else ''))
        causes = g.get('wasted_by_cause') or {}
        if any(causes.values()):
            out.append('  wasted by cause: ' + '  '.join(
                f'{c}={v}' for c, v in sorted(causes.items()) if v))
        if g.get('spec_shed_tokens'):
            out.append(f"  spec capacity shed (not computed): "
                       f"{g['spec_shed_tokens']} tokens")
        pt = g.get('per_tenant') or {}
        for tid in sorted(pt):
            row = pt[tid]
            out.append(f"  tenant {tid}: "
                       f"{row.get('delivered_tokens', 0)} delivered, "
                       f"{row.get('wasted_tokens', 0)} wasted")
    for site, r in sorted((snap.get('roofline') or {}).items()):
        if 'hbm_gbps' in r:
            line = (f"roofline[{site}]: decode "
                    f"{r['decode_bytes_per_iteration'] / 1e6:.2f} "
                    f"MB/iter -> {r['hbm_gbps']:.2f} GB/s")
            if r.get('mbu') is not None:
                line += (f"  MBU {r['mbu'] * 100:.1f}% of "
                         f"{r['peak_hbm_gbps']} GB/s peak")
            out.append(line)
        if 'prefill_tflops' in r:
            line = (f"roofline[{site}]: prefill "
                    f"{r['prefill_tflops']:.4f} TFLOP/s")
            if r.get('prefill_mfu') is not None:
                line += (f"  MFU {r['prefill_mfu'] * 100:.1f}% of "
                         f"{r['peak_tflops']} TFLOP/s peak")
            out.append(line)
    return '\n'.join(out)
