"""Serving replicas: the dp workers behind the cluster router.

Two deployments share one request-hosting core (`_EngineHost`):

  * `LocalReplica` — in-process replica over its own engine (and, on
    hardware, its own device slice / mp mesh). The router's `pump()`
    drives its engine steps, so a single process can dryrun an
    n-replica cluster deterministically (bench CPU mode, unit tests).
  * `ReplicaWorker` + `RemoteReplica` — a worker PROCESS serving the
    TCP control channel (channel.py), launched either by fleetrun
    (one worker per host, the PADDLE_TRAINER_* env the launcher
    already injects names the replica) or directly via
    `RemoteReplica.spawn`. The worker steps its engine in a loop and
    stamps a heartbeat before every sweep.

Hang handling (the PR-2 machinery wired into serving): a watchdog
thread watches the step-loop heartbeat; when the engine has work but
the heartbeat goes stale past `hang_timeout_s`, it writes a
`replica_hang_report` artifact — flight-recorder ring dump (the mp
collective journal on sharded replicas), all thread stacks, scheduler
census — through the fleet log conventions, and flags the replica
HUNG in its status. The router (router.py) sees the flag (or the
stale heartbeat itself, if the control plane died too) and DRAINS the
replica; the worker stays up for post-mortem instead of wedging the
cluster.
"""
import argparse
import collections
import json
import os
import sys
import threading
import time

from ..scheduler import AdmissionRejected, RequestState
from .channel import ControlClient, ControlServer
from .disagg import DisaggregatedEngine, build_engine

_TERMINAL = (RequestState.FINISHED, RequestState.ABORTED)


def _req_snapshot(rid, req):
    """The drain handoff record: everything a peer needs to resume
    this request (PR-9 resurrect semantics). ONE definition — the
    healthy drain path and the wedged-lock fallback both use it, so a
    new sampling option can't silently drop on one of them."""
    return {
        'rid': rid,
        'prompt': list(req.prompt),
        'generated': list(req.generated),
        'max_new_tokens': req.max_new_tokens,
        'eos_token_id': req.eos_token_id,
        'temperature': req.temperature,
        'top_k': req.top_k,
    }


def _live_requests(engine):
    if isinstance(engine, DisaggregatedEngine):
        return engine.live_requests()
    return [r for r in engine.scheduler.slots if r is not None]


def _waiting_requests(engine):
    if isinstance(engine, DisaggregatedEngine):
        return engine.waiting_requests()
    return list(engine.scheduler.waiting)


def _has_work(engine):
    if isinstance(engine, DisaggregatedEngine):
        return engine.has_work
    return engine.scheduler.has_work


def _decode_engine(engine):
    return engine.decode if isinstance(engine, DisaggregatedEngine) \
        else engine


def _prefix_digest(engine, limit=4096):
    if isinstance(engine, DisaggregatedEngine):
        # affinity cares where PREFILL would hit; decode-side pages
        # resurrect on handoff, so both pools count
        d = set(engine.prefill.pool.prefix_chain_hashes(limit))
        d.update(engine.decode.pool.prefix_chain_hashes(limit))
        return list(d)
    return engine.pool.prefix_chain_hashes(limit)


class _EngineHost:
    """Request hosting shared by LocalReplica and ReplicaWorker:
    submit/poll/status/drain/abort over one engine. Engine access is
    serialized by self._lock (the worker's channel threads race its
    step loop; LocalReplica is single-threaded but pays the uncontended
    lock for one code path)."""

    def __init__(self, engine, replica_id, clock=None):
        self.engine = engine
        self.replica_id = str(replica_id)
        self._clock = clock or time.perf_counter
        self._reqs = {}                 # rid str -> engine Request
        # finished requests keep reporting in poll() until evicted by
        # this capped ring — a poll reply lost to a channel timeout
        # (the client reconnects, the reply dies with the socket) must
        # not lose the completion forever
        self._done = collections.OrderedDict()      # rid -> view
        self._lock = threading.RLock()
        self._draining = False
        self._hung = False
        self._hang_reason = None
        self._beat = self._clock()

    # -- request plane -------------------------------------------------------
    def submit(self, prompt, opts, route_meta=None):
        if self._draining:
            raise RuntimeError(
                f"replica {self.replica_id} is draining")
        with self._lock:
            req = self.engine.submit(list(prompt), **dict(opts or {}))
            if route_meta and self.engine.tracer is not None:
                self.engine.tracer.record(req.id, 'route',
                                          **dict(route_meta))
        rid = str(req.id)
        self._reqs[rid] = req
        return rid

    DONE_RING = 512

    def poll(self):
        with self._lock:
            out = {}
            for rid, req in list(self._reqs.items()):
                view = {'generated': list(req.generated),
                        'state': req.state,
                        'done': req.state in _TERMINAL}
                out[rid] = view
                if view['done']:
                    # terminal views are final — park them in the
                    # ring and keep REPORTING them (idempotently)
                    # until evicted, so one lost reply can't lose
                    # the completion
                    del self._reqs[rid]
                    self._done[rid] = view
                    while len(self._done) > self.DONE_RING:
                        self._done.popitem(last=False)
            for rid, view in self._done.items():
                out.setdefault(rid, view)
        return out

    def status(self):
        now = self._clock()
        with self._lock:
            eng = _decode_engine(self.engine)
            live = [r for r in _live_requests(self.engine)
                    if r.state not in _TERMINAL]
            waiting = _waiting_requests(self.engine)
            pending_tokens = sum(
                max(r.max_new_tokens - len(r.generated), 0)
                + max(len(r.prompt) - r.prefilled, 0)
                for r in live + waiting)
            rate = (eng._decode_tokens / eng._decode_time
                    if eng._decode_time else 0.0)
            # serving ledger view (ISSUE 17): goodput counters + the
            # wall decomposition summary ride the heartbeat so the
            # router's cluster_snapshot() can aggregate without extra
            # RPCs. account() is None until the engine iterated.
            led = getattr(eng, 'ledger', None)
            goodput = led.goodput() if led is not None else None
            acct = led.account() if led is not None else None
            # disaggregated replica: the prefill engine priced the
            # prompt positions on ITS ledger — fold them in so the
            # replica reports the whole pipeline's token stream
            pre = getattr(self.engine, 'prefill', None)
            pre_led = getattr(pre, 'ledger', None) if pre is not None \
                else None
            if goodput is not None and pre_led is not None:
                g2 = pre_led.goodput()
                for k in ('emitted_tokens', 'delivered_tokens',
                          'wasted_tokens', 'spec_shed_tokens'):
                    goodput[k] += g2[k]
                for c, v in g2['wasted_by_cause'].items():
                    goodput['wasted_by_cause'][c] = \
                        goodput['wasted_by_cause'].get(c, 0) + v
                for tid, row in g2['per_tenant'].items():
                    dst = goodput['per_tenant'].setdefault(
                        tid, {'delivered_tokens': 0,
                              'wasted_tokens': 0})
                    dst['delivered_tokens'] += row['delivered_tokens']
                    dst['wasted_tokens'] += row['wasted_tokens']
                goodput['goodput_fraction'] = (
                    goodput['delivered_tokens']
                    / goodput['emitted_tokens']
                    if goodput['emitted_tokens'] else None)
            return {
                'replica_id': self.replica_id,
                'beat_age_s': now - self._beat,
                'hung': self._hung,
                'hang_reason': self._hang_reason,
                'draining': self._draining,
                'waiting': len(waiting),
                'in_flight': len(live),
                'pending_tokens': pending_tokens,
                'decode_tokens_per_sec': rate,
                'degrade_stage': eng.degrade_stage(),
                # fused decode (ISSUE 19): the router polls at window
                # granularity — a replica mid-window reports the last
                # completed window's counters, so beat_age_s can lag
                # by up to k iterations on a healthy fused engine
                'fused_k': eng._effective_fused_k(),
                'fused_windows_total': eng._fused_windows,
                'fused_iterations_total': eng._fused_iterations,
                'timeline': eng.timeline.summary(),
                'pool': {'pages_in_use': eng.pool.pages_in_use,
                         'num_pages': eng.pool.num_pages},
                'prefix_digest': _prefix_digest(self.engine),
                'goodput': goodput,
                'ledger': acct,
                # per-tenant accounting rides the heartbeat too so
                # Router.cluster_snapshot() can expose the
                # per-replica-bucket N x-quota effect (ISSUE 18)
                'tenancy': eng._tenancy_stats(),
            }

    def metrics(self):
        """Compact per-replica metrics snapshot for cluster federation
        (ISSUE 18): the scalar ptpu_serve_* series this engine WOULD
        publish, straight off engine.stats() via the same declarative
        table publish() uses — NOT read back from the process-global
        registry, which in-process LocalReplicas share and would
        cross-contaminate. The router merges these under a `replica`
        label into its federated registry."""
        from .. import metrics as _serve_metrics
        with self._lock:
            eng = _decode_engine(self.engine)
            stats = eng.stats()
            series = _serve_metrics.scalar_series(stats)
            led = getattr(eng, 'ledger', None)
            if led is not None:
                acct = led.account()
                if acct and acct.get('host_bound_fraction') is not None:
                    series['ptpu_serve_ledger_host_bound_fraction'] = \
                        acct['host_bound_fraction']
                good = led.goodput()
                gf = (good or {}).get('goodput_fraction')
                if gf is not None:
                    series['ptpu_serve_goodput_fraction'] = gf
            return {'replica_id': self.replica_id,
                    'beat_age_s': self._clock() - self._beat,
                    'series': series}

    def drain(self):
        """Stop admitting, snapshot + abort every unfinished request.
        The snapshots (prompt, tokens generated so far, remaining
        opts) are what the router resubmits to a peer — the PR-9
        resurrect path, one replica over."""
        self._draining = True
        snaps = []
        with self._lock:
            for rid, req in list(self._reqs.items()):
                if req.state in _TERMINAL:
                    continue
                snaps.append(_req_snapshot(rid, req))
                try:
                    self.engine.abort(req, reason='drained')
                except Exception:           # noqa: BLE001
                    pass
        return snaps

    def prefetch(self, prompt):
        """Advisory host-tier warm (ISSUE 20): the router's
        prefix-affinity hint arrives BEFORE the request and resurrects
        host-resident prefix pages into parked device pages, so the
        submit that follows prefix-hits device pages with the transfer
        off its critical path. Purely advisory — a tierless engine (or
        one whose pages were never spilled) warms nothing, and the
        warm itself never evicts or preempts. Serialized with the step
        loop by self._lock like every other pool mutation."""
        pool = getattr(self.engine, 'pool', None)
        if pool is None or getattr(pool, 'host_tier', None) is None:
            return {'warmed_pages': 0}
        with self._lock:
            prompt = [int(t) for t in prompt]
            n = pool.warm_prefix(prompt, limit=len(prompt) - 1)
        return {'warmed_pages': int(n)}

    def abort(self, rid):
        req = self._reqs.get(str(rid))
        if req is None:
            return False
        with self._lock:
            return bool(self.engine.abort(req))

    def export_trace(self, jsonl_path):
        with self._lock:
            return self.engine.export_trace(jsonl_path=jsonl_path)

    def shutdown(self):
        with self._lock:
            return self.engine.shutdown()


class LocalReplica(_EngineHost):
    """In-process replica: the router pumps its engine directly."""

    def __init__(self, engine, replica_id, clock=None):
        super().__init__(engine, replica_id, clock=clock)
        self._inject_hang = False

    def inject_hang(self):
        """Test hook mirroring ReplicaWorker's: pump() stops stamping
        the heartbeat (and stepping), exactly what a wedged device
        dispatch looks like to the router's watchdog + the
        replica_heartbeat_stale alert rule."""
        self._inject_hang = True
        return {'ok': True}

    def pump(self):
        if self._inject_hang:
            return False
        with self._lock:
            self._beat = self._clock()
            if _has_work(self.engine):
                self.engine.step()
                return True
        return False


class ReplicaWorker(_EngineHost):
    """A replica process: control channel + engine step loop +
    hang watchdog. `run()` blocks in the step loop (the worker
    process's main thread); `start()` runs it on a thread for
    in-process tests."""

    def __init__(self, engine, replica_id, port=0,
                 hang_timeout_s=10.0, report_dir=None, clock=None):
        super().__init__(engine, replica_id, clock=clock)
        self.hang_timeout_s = float(hang_timeout_s)
        self.report_dir = report_dir
        self.last_hang_report_path = None
        self._stop = threading.Event()
        self._inject_hang = False
        self.server = ControlServer(self._handle, port=port).start()
        self.port = self.server.port
        self._watchdog = threading.Thread(
            target=self._watch_loop, name='replica-watchdog',
            daemon=True)
        self._watchdog.start()
        self._loop_thread = None

    # -- control channel -----------------------------------------------------
    def _handle(self, msg):
        op = msg.get('op')
        if op == 'submit':
            try:
                return {'rid': self.submit(msg['prompt'],
                                           msg.get('opts') or {},
                                           msg.get('route'))}
            except AdmissionRejected as e:
                # structured refusal, NOT a channel error: the engine
                # turned the request away (deadline-aware admission,
                # ISSUE 15) — the router must re-raise it as a
                # RouterRejected with the hint, not drain a healthy
                # replica
                return {'rejected': {
                    'reason': e.reason,
                    'retry_after_s': e.retry_after_s,
                    'estimated_s': e.estimated_s,
                    'deadline_s': e.deadline_s}}
        if op == 'poll':
            return {'reqs': self.poll()}
        if op == 'status':
            return self.status()
        if op == 'metrics':
            return self.metrics()
        if op == 'drain':
            return {'inflight': self.drain()}
        if op == 'abort':
            return {'ok': self.abort(msg.get('rid'))}
        if op == 'prefetch':
            # advisory host-tier warm (ISSUE 20) — never an error
            return self.prefetch(msg.get('prompt') or [])
        if op == 'export_trace':
            return {'path': self.export_trace(msg['path'])['jsonl']}
        if op == 'inject_hang':
            # test hook: wedge the step loop (NOT the control plane),
            # exactly what a stuck device dispatch looks like
            self._inject_hang = True
            return {'ok': True}
        if op == 'shutdown':
            self._stop.set()
            return {'ok': True}
        raise ValueError(f"unknown control op {op!r}")

    # status()/drain() intentionally run on the CONTROL thread without
    # waiting for the step loop: when the step loop is wedged inside a
    # dispatch, the lock may be held forever — health probes must not
    # join the hang. The base-class lock methods cover the healthy
    # path; the wedged path reads host lists that Python mutates
    # atomically enough for a diagnostic.
    def status(self):
        if self._lock.acquire(timeout=0.5):
            try:
                return _EngineHost.status(self)
            finally:
                self._lock.release()
        return {
            'replica_id': self.replica_id,
            'beat_age_s': self._clock() - self._beat,
            'hung': self._hung,
            'hang_reason': self._hang_reason,
            'draining': self._draining,
            'waiting': len(_waiting_requests(self.engine)),
            'in_flight': len([r for r in _live_requests(self.engine)
                              if r.state not in _TERMINAL]),
            'pending_tokens': 0,
            'decode_tokens_per_sec': 0.0,
            'degrade_stage': 0,
            'fused_k': 1,
            'fused_windows_total': 0,
            'fused_iterations_total': 0,
            'timeline': {},
            'pool': {},
            'prefix_digest': None,      # keep the router's last view
            'tenancy': None,
        }

    def metrics(self):
        # same wedged-lock discipline as status(): a federation poll
        # must not join a hung step loop — stale beat_age_s and an
        # empty series dict ARE the signal (staleness stamps go quiet)
        if self._lock.acquire(timeout=0.5):
            try:
                return _EngineHost.metrics(self)
            finally:
                self._lock.release()
        return {'replica_id': self.replica_id,
                'beat_age_s': self._clock() - self._beat,
                'series': {}}

    def drain(self):
        if self._lock.acquire(timeout=0.5):
            try:
                return _EngineHost.drain(self)
            finally:
                self._lock.release()
        # wedged: report what we know, abort nothing (the engine
        # thread owns the lock) — the router resubmits from snapshots
        self._draining = True
        return [_req_snapshot(rid, req)
                for rid, req in list(self._reqs.items())
                if req.state not in _TERMINAL]

    # -- step loop + watchdog ------------------------------------------------
    def run(self):
        while not self._stop.is_set():
            if self._inject_hang:
                # simulated wedged dispatch: no heartbeat, no lock
                time.sleep(0.05)
                continue
            self._beat = self._clock()
            with self._lock:
                busy = _has_work(self.engine)
                if busy:
                    self.engine.step()
            if not busy:
                time.sleep(0.002)

    def start(self):
        self._loop_thread = threading.Thread(
            target=self.run, name='replica-step-loop', daemon=True)
        self._loop_thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.server.close()

    def _watch_loop(self):
        fired = False
        while not self._stop.is_set():
            time.sleep(min(self.hang_timeout_s / 4, 0.5))
            age = self._clock() - self._beat
            busy = (self._inject_hang
                    or bool(self._reqs))
            if busy and age > self.hang_timeout_s and not fired:
                fired = True
                self._fire_watchdog(
                    f"step loop heartbeat stale for {age:.1f}s "
                    f"(timeout {self.hang_timeout_s}s)")

    def _fire_watchdog(self, reason):
        """Diagnose + dump a wedged step loop (PR-2 conventions):
        flight-recorder ring (the collective journal on mp-sharded
        replicas — which gather never completed), every thread stack
        (where the loop is stuck), scheduler census. The artifact is
        what `health_dump <path>` renders; the status flag is what the
        router drains on."""
        self._hung = True
        self._hang_reason = reason
        doc = {'kind': 'replica_hang_report',
               'replica_id': self.replica_id,
               'reason': reason,
               'hang_timeout_s': self.hang_timeout_s,
               'waiting': len(_waiting_requests(self.engine)),
               'in_flight': len(_live_requests(self.engine)),
               'requests': {rid: {'state': r.state,
                                  'tokens_generated': len(r.generated)}
                            for rid, r in list(self._reqs.items())}}
        try:
            from ...distributed import flight_recorder as _fr
            doc['flight_recorder'] = _fr.recorder().dump()
            doc['stacks'] = _fr._thread_stacks()
        except Exception as e:              # noqa: BLE001
            doc['flight_recorder_error'] = repr(e)[:200]
        d = (self.report_dir
             or os.environ.get('PTPU_SERVE_REPORT_DIR')
             or os.environ.get('FLEET_LOG_DIR'))
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f'replica_hang.{self.replica_id}.json')
                with open(path, 'w') as f:
                    json.dump(doc, f, indent=1, default=str)
                self.last_hang_report_path = path
            except OSError:
                pass
        try:
            from ...distributed.fleet.utils.log_util import log_json
            log_json('replica_hang', level='error',
                     msg=f"serving replica {self.replica_id} hung: "
                         f"{reason}",
                     replica=self.replica_id, reason=reason,
                     report_path=self.last_hang_report_path)
        except Exception:                   # noqa: BLE001
            pass

    def pump(self):
        return False        # the worker's own loop does the stepping


class RemoteReplica:
    """Router-side handle for a ReplicaWorker process."""

    def __init__(self, replica_id, host, port, proc=None,
                 timeout=30.0):
        self.replica_id = str(replica_id)
        self.client = ControlClient(host, port, timeout=timeout)
        self.proc = proc

    @classmethod
    def spawn(cls, replica_id, model_config, engine_config=None,
              seed=0, hang_timeout_s=10.0, env=None,
              ready_timeout_s=300.0):
        """Start `python -m paddle_tpu.serving.cluster.replica` and
        connect once it prints REPLICA_READY (model build + compile
        warmup happen before readiness, so the router never sees a
        cold-compile heartbeat stall)."""
        import subprocess
        cmd = [sys.executable, '-u', '-m',
               'paddle_tpu.serving.cluster.replica',
               '--replica-id', str(replica_id), '--port', '0',
               '--seed', str(seed),
               '--hang-timeout', str(hang_timeout_s),
               '--model-config', json.dumps(model_config),
               '--engine-config', json.dumps(engine_config or {})]
        full_env = dict(os.environ)
        full_env.setdefault('JAX_PLATFORMS', 'cpu')
        full_env.update(env or {})
        proc = subprocess.Popen(cmd, env=full_env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        # a reader THREAD feeds a queue so the deadline below holds
        # even against a worker that wedges silently mid-warmup —
        # readline() on the main thread would block past any timeout
        # (exactly the hang class this module defends against)
        import queue as _queue
        q = _queue.Queue()

        def _reader():
            for line in proc.stdout:        # drains post-ready too
                q.put(line)
            q.put(None)

        threading.Thread(target=_reader, daemon=True).start()
        deadline = time.time() + ready_timeout_s
        port = None
        lines = []
        while time.time() < deadline:
            try:
                line = q.get(timeout=min(
                    1.0, max(deadline - time.time(), 0.01)))
            except _queue.Empty:
                if proc.poll() is not None:
                    break
                continue
            if line is None:
                break
            lines.append(line.rstrip())
            if line.startswith('REPLICA_READY'):
                port = int(line.split('port=')[1].strip())
                break
        if port is None:
            proc.kill()
            tail = '\n'.join(lines[-20:])
            raise RuntimeError(
                f"replica {replica_id} never became ready:\n{tail}")
        return cls(replica_id, '127.0.0.1', port, proc=proc)

    def submit(self, prompt, opts, route_meta=None):
        reply = self.client.call({'op': 'submit',
                                  'prompt': [int(t) for t in prompt],
                                  'opts': opts,
                                  'route': route_meta})
        rej = reply.get('rejected')
        if rej is not None:
            raise AdmissionRejected(
                rej.get('reason', 'rejected'),
                retry_after_s=rej.get('retry_after_s'),
                estimated_s=rej.get('estimated_s'),
                deadline_s=rej.get('deadline_s'))
        return reply['rid']

    def poll(self):
        return self.client.call({'op': 'poll'}, timeout=30.0)['reqs']

    def status(self):
        return self.client.call({'op': 'status'}, timeout=5.0)

    def metrics(self):
        return self.client.call({'op': 'metrics'}, timeout=5.0)

    def drain(self):
        return self.client.call({'op': 'drain'},
                                timeout=5.0)['inflight']

    def abort(self, rid):
        return self.client.call({'op': 'abort', 'rid': rid})['ok']

    def prefetch(self, prompt):
        return self.client.call({'op': 'prefetch',
                                 'prompt': [int(t) for t in prompt]},
                                timeout=30.0)

    def export_trace(self, jsonl_path):
        return self.client.call({'op': 'export_trace',
                                 'path': jsonl_path}, timeout=30.0)

    def inject_hang(self):
        return self.client.call({'op': 'inject_hang'})

    def pump(self):
        return False        # remote worker steps itself

    def shutdown(self):
        try:
            self.client.call({'op': 'shutdown'}, timeout=5.0)
        except Exception:                   # noqa: BLE001
            pass
        self.client.close()
        if self.proc is not None:
            try:
                self.proc.wait(timeout=10)
            except Exception:               # noqa: BLE001
                self.proc.kill()


# ---------------------------------------------------------------------------
# worker entrypoint: python -m paddle_tpu.serving.cluster.replica
# ---------------------------------------------------------------------------
def main(argv=None):
    ap = argparse.ArgumentParser(
        'paddle_tpu serving replica worker')
    ap.add_argument('--replica-id',
                    default=os.environ.get('PADDLE_TRAINER_ID', '0'))
    ap.add_argument('--port', type=int, default=0)
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--hang-timeout', type=float, default=10.0)
    ap.add_argument('--model-config', default='{}',
                    help='GPTConfig kwargs (JSON)')
    ap.add_argument('--engine-config', default='{}',
                    help='ServingConfig kwargs (JSON)')
    ap.add_argument('--mp', type=int, default=1,
                    help='mp degree inside this replica (device-slice '
                         'mesh; the model is built under a matching '
                         'hcg)')
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ServingConfig

    mesh = None
    if args.mp > 1:
        import paddle_tpu.distributed.fleet as fleet_mod
        from paddle_tpu.distributed import topology_runtime
        from paddle_tpu.distributed.fleet.base.topology import (
            CommunicateTopology, HybridCommunicateGroup)
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "model"],
            [1, 1, 1, args.mp])
        fleet_mod.fleet._topology = topo
        fleet_mod.fleet._hcg = HybridCommunicateGroup(topo)
        mesh = topology_runtime.build_mesh(['mp'], [args.mp])

    paddle.seed(args.seed)
    model = GPTForCausalLM(GPTConfig(**json.loads(args.model_config)))
    model.eval()
    engine = build_engine(model,
                          ServingConfig(**json.loads(
                              args.engine_config)), mesh=mesh)
    worker = ReplicaWorker(engine, args.replica_id, port=args.port,
                           hang_timeout_s=args.hang_timeout)
    # compile warmup BEFORE readiness: the standard step shapes
    # (prefill chunk + batched decode) must not stall the heartbeat
    # under first live traffic
    engine.generate([[1, 2, 3]], max_new_tokens=2, top_k=0)
    engine.reset_stats()
    print(f'REPLICA_READY port={worker.port}', flush=True)
    try:
        worker.run()
    finally:
        worker.stop()


if __name__ == '__main__':
    main()
