"""Prefill/decode disaggregation (ISSUE 11 tentpole part 4).

Two engines over one model, each with its own paged pool:

  * the PREFILL engine runs chunked prefill only — requests are
    submitted here, stream through prefill chunks, and emit their
    first token exactly like the unified engine;
  * the moment a request finishes prefill (state RUNNING), its KV
    pages are STREAMED into the decode pool (page_stream.py — chunked
    gather/scatter on the page axis, int8 scale buffers ride along,
    bit-identical rows) and the request is ADOPTED into a decode slot
    (`ServingEngine.adopt_request`); its prefill-side pages release.

On a real cluster the two pools live on different device slices, so
the stream is the prefill→decode page handoff of disaggregated
serving; in one process it is a device copy with the same layout —
which is what makes the bit-exactness testable on CPU.

Decode-side prefix sharing still works: pages the decode pool already
holds for a shared prefix are mapped instead of re-streamed (only the
uncovered tail pages move), and streamed pages join the decode pool's
prefix index, so the second request behind a system prompt streams
almost nothing.

The unified engine's semantics are preserved: greedy outputs are
token-identical to a single `ServingEngine` on the same stream
(asserted in tests/test_serving_cluster.py). Preemption on the decode
side falls back to re-prefill ON the decode engine (the PR-9 resurrect
path) — correctness first; a re-handoff would need cross-pool
eviction coordination for zero benefit at preemption rates worth
having.
"""
import math
import time

from ..engine import ServingConfig, ServingEngine
from ..kv_pool import PoolExhausted
from ..scheduler import AdmissionRejected, RequestState
from ...core import monitor as _m
from .page_stream import stream_kv_pages


def build_engine(model, config=None, mesh=None, **cfg_kw):
    """ServingEngine, or DisaggregatedEngine when
    config.disaggregate — the one constructor replicas use."""
    if config is None:
        config = ServingConfig(**cfg_kw)
    elif cfg_kw:
        raise ValueError("pass either config or knobs, not both")
    if config.disaggregate:
        return DisaggregatedEngine(model, config, mesh=mesh)
    return ServingEngine(model, config, mesh=mesh)


class DisaggregatedEngine:
    """Drop-in engine facade: submit/step/generate/abort/stats/...
    match ServingEngine's surface, dispatching prefill work to the
    prefill engine and decode work to the decode engine."""

    def __init__(self, model, config=None, mesh=None, **cfg_kw):
        if config is None:
            config = ServingConfig(**cfg_kw)
        elif cfg_kw:
            raise ValueError("pass either config or knobs, not both")
        self.model = model
        self.config = config
        self.decode = ServingEngine(model, _variant(config,
                                                   disaggregate=False),
                                    mesh=mesh)
        # prefill side: its own (smaller) slot table and pool; trace
        # off at build, then SHARE the decode tracer + clock so a
        # request's journal is one stream across the handoff
        pcfg = _variant(config, disaggregate=False,
                        max_batch_size=config.prefill_slots,
                        trace=False,
                        clock=self.decode._clock)
        # its own ledger/gap-monitor site: both engines live in this
        # process and the registries are latest-wins per site, so the
        # prefill side must not shadow the decode engine's 'serve' row
        self.prefill = ServingEngine(model, pcfg, mesh=mesh,
                                     ledger_site='serve_prefill')
        self.prefill.tracer = self.decode.tracer
        # the facade checks deadline admission itself (combined
        # backlogs at the decode rate, submit() below) — the prefill
        # engine's local re-check would see neither and could
        # spuriously reject once its own decode rate turns nonzero
        self.prefill.deadline_admission = False
        # ONE degradation ladder for the pipeline: both engines feed
        # it their pressure and read one consistent stage, so the
        # prefill-side lever (chunk shrink) and the decode-side one
        # (spec shed) move together and each transition traces once
        if self.decode._ladder is not None:
            self.prefill._ladder = self.decode._ladder
            # stage-3 weighted eviction arms on BOTH pools no matter
            # which side observes the transition (ISSUE 16 satellite:
            # the observing engine used to arm only its own pool)
            self.decode._stage3_pools = (self.prefill.pool,)
            self.prefill._stage3_pools = (self.decode.pool,)
        # one publisher: the global ptpu_serve_* gauges reflect the
        # decode engine (where requests retire and most SLO samples
        # land); the prefill side's pending histogram samples (TTFT is
        # stamped during prefill!) forward into the decode engine's
        # buffers so the cluster-wide histograms still see them
        def _forward_publish(eng=self.prefill):
            self.decode._new_ttfts_s.extend(eng._new_ttfts_s)
            eng._new_ttfts_s.clear()
            for k, v in eng._new_slo.items():
                self.decode._new_slo[k].extend(v)
                v.clear()
            # tenant-labeled samples too (ISSUE 15): a tenanted
            # request aborted prefill-side must still reach the
            # ptpu_serve_tenant_* histograms
            for tid, d in eng._new_tenant_slo.items():
                dst = self.decode._new_tenant_slo.setdefault(
                    tid, {'queue_wait_s': [], 'e2e_s': []})
                for k, v in d.items():
                    dst[k].extend(v)
                    v.clear()
            eng._last_publish = eng._clock()
            eng._last_publish_wall = _m._time_fn()
        self.prefill.publish_metrics = _forward_publish
        self._pending = []          # prefilled, waiting for a slot
        self._handoffs = 0
        self._streamed_pages = 0

    # -- engine surface ------------------------------------------------------
    @property
    def pool(self):
        return self.decode.pool

    @property
    def timeline(self):
        return self.decode.timeline

    @property
    def tracer(self):
        return self.decode.tracer

    @property
    def scheduler(self):
        # the decode scheduler is "the" scheduler for occupancy views;
        # queue state lives prefill-side (see has_work / waiting)
        return self.decode.scheduler

    @property
    def has_work(self):
        return (self.prefill.scheduler.has_work or bool(self._pending)
                or self.decode.scheduler.has_work)

    def waiting_requests(self):
        return list(self.prefill.scheduler.waiting)

    def live_requests(self):
        return ([r for r in self.prefill.scheduler.slots
                 if r is not None] + list(self._pending)
                + [r for r in self.decode.scheduler.slots
                   if r is not None])

    def submit(self, prompt_ids, **kw):
        # deadline-aware admission (ISSUE 15) against the WHOLE
        # pipeline: the prefill engine's own estimate only sees its
        # side (and its decode rate is unrepresentative — requests
        # hand off right after prefill), so estimate here with the
        # decode engine's observed rate over both backlogs; the
        # prefill engine's own check is disabled (deadline_admission
        # = False above), so this is the ONE gate
        deadline = kw.get('deadline_s')
        if deadline is not None:
            rate = self.decode.decode_rate()
            if rate > 0.0:
                bill = len(prompt_ids) + int(kw.get('max_new_tokens',
                                                    32))
                est = (self.prefill.pending_tokens()
                       + self.decode.pending_tokens() + bill) / rate
                if est > deadline:
                    self.decode._deadline_rejects += 1
                    tid = kw.get('tenant_id')
                    if tid is not None:
                        self.decode._tstat(tid)['deadline_rejects'] \
                            += 1
                    raise AdmissionRejected(
                        'deadline_unmet',
                        retry_after_s=est - deadline,
                        estimated_s=est, deadline_s=deadline)
        return self.prefill.submit(prompt_ids, **kw)

    def step(self):
        """One cluster-internal iteration: a prefill sweep, then the
        handoff scan, then a decode sweep."""
        if self.prefill.scheduler.has_work:
            self.prefill.step()
        for req in list(self.prefill.scheduler.slots):
            if req is not None and req.state == RequestState.RUNNING:
                self._handoff(req)
        while self._pending:
            if not self.decode.adopt_request(self._pending[0]):
                break
            self._pending.pop(0)
        if self.decode.scheduler.has_work:
            self.decode.step()

    def _handoff(self, req):
        """Stream req's finished prefill pages into the decode pool and
        queue it for adoption. Decode-resident shared-prefix pages are
        mapped, not re-streamed — only the uncovered tail moves."""
        src_pool, dst_pool = self.prefill.pool, self.decode.pool
        ps = src_pool.page_size
        L = len(req.prompt)
        src_pages = src_pool.page_table(req.id)
        cached = dst_pool.match_and_map(req.id, req.tokens, limit=L)
        n_cached = cached // ps
        # decode-pool pressure preempts decode-side victims, exactly
        # like a local prefill allocation would. With tenants, every
        # decode resident may outrank this request (no victim, and the
        # engine's yield path can't fire — req holds a PREFILL slot,
        # not a decode one): DEFER the handoff instead of letting
        # PoolExhausted crash the step loop — req keeps its prefill
        # slot and pages, and this scan retries next sweep once decode
        # residents retire. An empty decode slot table means nobody
        # will ever free pages — that is the genuine too-big case and
        # still raises.
        try:
            self.decode._ensure_or_preempt(req, L)
        except PoolExhausted:
            if not any(r is not None
                       for r in self.decode.scheduler.slots):
                raise
            dst_pool.release(req.id)    # drop the mapped/partial pages
            return
        dst_pages = dst_pool.page_table(req.id)
        n = min(len(src_pages), len(dst_pages))
        if n > n_cached:
            t0 = time.perf_counter()
            self.decode.pool.kv = stream_kv_pages(
                src_pool.kv, dst_pool.kv,
                src_pages[n_cached:n], dst_pages[n_cached:n],
                chunk_pages=self.config.stream_chunk_pages)
            # ledger: the handoff runs between the two engines' sweeps,
            # so the stream wall lands in the decode engine's NEXT
            # iteration as its page_stream component
            self.decode.ledger.note_page_stream(
                time.perf_counter() - t0)
            self._streamed_pages += n - n_cached
        # release the prefill side WITHOUT retiring: the request lives
        # on, its journal continues on the decode engine
        i = self.prefill.scheduler.slot_of(req)
        self.prefill.scheduler.slots[i] = None
        src_pool.release(req.id)
        self._handoffs += 1
        _m.counter('ptpu_serve_pd_handoffs_total',
                   help='prefill->decode request handoffs '
                        '(lifetime)').inc()
        self._pending.append(req)

    def generate(self, prompts, max_new_tokens=32, eos_token_id=None,
                 temperature=1.0, top_k=0, max_steps=None):
        reqs = [self.submit(p, max_new_tokens=max_new_tokens,
                            eos_token_id=eos_token_id,
                            temperature=temperature, top_k=top_k)
                for p in prompts]
        guard = max_steps or 16 * (max_new_tokens + 4) * max(
            1, math.ceil(len(reqs) / self.config.max_batch_size))
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > guard:
                raise RuntimeError(
                    f"disaggregated loop did not drain in {guard} steps")
        return [r.output_ids() for r in reqs]

    def abort(self, req, reason='aborted'):
        if req in self._pending:
            self._pending.remove(req)
            return self.decode.abort(req, reason=reason)
        if req in self.prefill.scheduler.waiting \
                or req in self.prefill.scheduler.slots:
            return self.prefill.abort(req, reason=reason)
        return self.decode.abort(req, reason=reason)

    def stats(self):
        s = self.decode.stats()
        ps = self.prefill.stats()
        s['pd_disaggregated'] = True
        s['pd_handoffs_total'] = self._handoffs
        s['pd_streamed_pages_total'] = self._streamed_pages
        s['pd_pending'] = len(self._pending)
        # prefill work happens on the other engine — surface its side
        s['prefill_tokens_total'] = ps['prefill_tokens_total']
        s['prefill_chunks_total'] = ps['prefill_chunks_total']
        s['prefix_hits_total'] += ps['prefix_hits_total']
        s['prefix_misses_total'] += ps['prefix_misses_total']
        s['prefix_hit_tokens_total'] += ps['prefix_hit_tokens_total']
        # tenancy accounting happens where admission runs — the
        # PREFILL engine (quota debits/deferrals, deadline misses);
        # decode-side rows carry charged preemptions from handoff
        # pressure. Merge both so the published gauges see the truth.
        for key in ('quota_deferrals_total', 'preemptions_charged_total',
                    'deadline_rejects_total', 'deadline_misses_total'):
            s[key] += ps[key]
        for tid, row in ps['tenancy'].get('tenants', {}).items():
            dst = s['tenancy']['tenants'].setdefault(tid, {})
            for k, v in row.items():
                if k in ServingEngine._blank_tstat():
                    dst[k] = dst.get(k, 0) + v
                else:
                    dst.setdefault(k, v)
        s['pd_prefill_pool'] = {
            'pages_in_use': ps['pool']['pages_in_use'],
            'high_water': ps['pool']['high_water'],
            'num_pages': ps['pool']['num_pages'],
        }
        return s

    def request_table(self):
        return self.decode.request_table()

    def publish_metrics(self):
        self.decode.publish_metrics()

    def reset_stats(self):
        self.prefill.reset_stats()
        self.decode.reset_stats()

    def export_trace(self, jsonl_path=None, chrome_path=None):
        return self.decode.export_trace(jsonl_path=jsonl_path,
                                        chrome_path=chrome_path)

    def shutdown(self):
        self.prefill.shutdown()
        return self.decode.shutdown()


def _variant(config, **overrides):
    """Copy a ServingConfig with overrides (configs are plain
    attribute bags — rebuild through __init__ so validation runs)."""
    import inspect
    kw = {}
    for name in inspect.signature(ServingConfig.__init__).parameters:
        if name == 'self':
            continue
        kw[name] = getattr(config, name)
    kw.update(overrides)
    return ServingConfig(**kw)
