"""Prefix-affinity request router — the cluster's async front-end.

Placement runs two signals the single-engine PRs built:

  * PREFIX AFFINITY (first): the prompt's full pages hash into a
    radix chain (`kv_pool.chain_hashes` — the same link hash every
    replica derives from its own prefix index via
    `prefix_chain_hashes`); the replica whose published digest holds
    the DEEPEST chain prefix already has those KV pages resident, so
    routing there turns whole prefill chunks into page maps (PR-9).
    The router also adds a placed prompt's hashes to its local view of
    the target's digest immediately, so a burst of shared-prefix
    requests lands together without waiting for the next status
    refresh.
  * LEAST OCCUPANCY (fallback): each replica's published
    SchedulerTimeline summary + queue depth (PR-6's occupancy-feedback
    signal) — fewest (waiting + in-flight), ties to lowest mean
    occupancy.

Backpressure and overload: a replica whose queue exceeds `max_queue`
is skipped — an affinity hit that would land on a saturated replica
SPILLS to the least-loaded one (counted separately: spills measure
affinity broken by load). When EVERY healthy replica is over the
bound — or `deadline_bound_s` is set and the fastest replica's
estimated queue drain exceeds it — the router REJECTS at submit
(RouterRejected) instead of queueing forever: reject-early beats
blowing every request's deadline at the back of a hopeless queue.

Health + drain: replicas publish a heartbeat with status; a stale
heartbeat / unresponsive channel / worker-watchdog flag marks the
replica HUNG (its own watchdog has dumped diagnostics by then —
replica.py), the router stops placement and DRAINS it: every
in-flight request is resubmitted to a peer as prompt + tokens
generated so far (the PR-9 resurrect path — re-prefill prefix-hits
the peer's cache, and greedy continuations are token-identical), so a
wedged replica costs latency, not requests.

Counters: ptpu_route_{affinity_hits,least_loaded,spills,rejects,
drains}_total through core.monitor; `cluster_snapshot()` is the
health_dump/bench view.

Metrics federation (ISSUE 18): per-replica registries live in worker
processes, so the router keeps its own FEDERATED MetricsRegistry —
`refresh()` feeds it the status-derived signals (heartbeat age, queue,
occupancy, pool pressure) and `federate()` merges each replica's
compact `metrics` channel-op snapshot, every series under a `replica`
label. `cluster_prometheus_text()` / `serve_metrics_http()` expose ONE
scrape for the whole cluster; a `MetricHistory` over the federated
registry plus an `AlertManager` running `router_rules()` (heartbeat
staleness, cluster pool pressure, occupancy imbalance, drain/resubmit
storms, spill rate) complete the input plane the ROADMAP autoscaler
will consume.
"""
import collections
import itertools
import time

from ..kv_pool import chain_hashes
from ..scheduler import AdmissionRejected
from ...core import monitor as _m
from ...core.alerts import AlertManager, router_rules


class RouterRejected(RuntimeError):
    """All replicas over their backpressure/deadline bound — retry
    later (the cluster is telling you now, not after the deadline).

    Structured (ISSUE 15 satellite): `reason` is machine-readable
    ('backpressure' | 'deadline_unmet' | 'no_healthy_replicas'),
    `retry_after_s` the router's own estimate of when a retry can
    land — computed from observed per-replica decode rates and queue
    depths (time until the fastest replica finishes one queued
    request), or forwarded from an engine-side AdmissionRejected.
    None when nothing is known (cold cluster / no healthy replicas).
    serve() backs off by the hint instead of a fixed sleep; the bench
    leg records hint accuracy."""

    def __init__(self, message, reason='backpressure',
                 retry_after_s=None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


_route_ids = itertools.count()

_COUNTERS = {
    'affinity': ('ptpu_route_affinity_hits_total',
                 'placements on the replica already holding the '
                 'prompt prefix pages'),
    'least_loaded': ('ptpu_route_least_loaded_total',
                     'placements by occupancy fallback (no prefix '
                     'affinity)'),
    'spill': ('ptpu_route_spills_total',
              'affinity placements diverted by backpressure'),
    'reject': ('ptpu_route_rejects_total',
               'submissions rejected early (all replicas over bound)'),
    'drain': ('ptpu_route_drains_total',
              'replicas drained (hung or operator-requested)'),
    'resubmit': ('ptpu_route_resubmits_total',
                 'in-flight requests moved to a peer by a drain'),
    'prefetch_hint': ('ptpu_route_prefetch_hints_total',
                      'advisory host-tier prefetch hints sent ahead '
                      'of affinity placements (ISSUE 20)'),
}


class RoutedRequest:
    """The router-side record of one request: where it went and every
    token streamed back so far. Survives drains — `tokens` accumulates
    across resubmissions, so `output_ids()` is the same contract as
    the engine's Request."""

    def __init__(self, prompt, opts):
        self.id = next(_route_ids)
        self.prompt = list(prompt)
        self.opts = dict(opts)
        self.tokens = []                # generated, across replicas
        self.replica_id = None
        self.remote_rid = None
        self.decision = None
        self.resubmits = 0
        # tokens generated BEFORE the current dispatch: a resubmitted
        # request's replica reports only its own continuation, which
        # appends after this prefix
        self._dispatch_base = 0
        self.done = False
        self.submit_t = None
        self.finish_t = None

    @property
    def budget_left(self):
        return self.opts.get('max_new_tokens', 32) - len(self.tokens)

    def output_ids(self):
        return self.prompt + self.tokens


class ClusterRouter:
    def __init__(self, replicas, page_size, max_queue=8,
                 deadline_bound_s=None, hang_timeout_s=10.0,
                 refresh_interval_s=0.25, clock=None,
                 history_capacity=512, alert_rules=None,
                 report_dir=None):
        if not replicas:
            raise ValueError("a cluster needs at least one replica")
        self.page_size = int(page_size)
        self.max_queue = int(max_queue)
        self.deadline_bound_s = deadline_bound_s
        self.hang_timeout_s = float(hang_timeout_s)
        self.refresh_interval_s = float(refresh_interval_s)
        self._clock = clock or time.perf_counter
        self._replicas = {r.replica_id: r for r in replicas}
        if len(self._replicas) != len(replicas):
            raise ValueError("duplicate replica ids")
        # affinity signal: the digest each replica PUBLISHED last
        # (replaced wholesale every refresh, so pool evictions age
        # out) plus a short-lived optimistic overlay for prompts the
        # router placed that the replica hasn't indexed/published yet
        # — entries survive OPTIMISTIC_GENERATIONS refreshes, then
        # drop (re-added on the next same-prefix submit if still hot)
        self._digest = {rid: set() for rid in self._replicas}
        self._optimistic = {rid: {} for rid in self._replicas}
        self._refresh_gen = {rid: 0 for rid in self._replicas}
        self._status = {rid: {} for rid in self._replicas}
        self._drained = set()
        self._hung = set()
        # request bookkeeping is BOUNDED for a long-lived front-end:
        # open requests only in _open/_by_replica (pruned the moment
        # they finish), a capped ring of finished ones for the SLO
        # view, lifetime counters for the snapshot
        self._open = {}                 # route id -> RoutedRequest
        self._recent = collections.deque(maxlen=1024)
        self._by_replica = {rid: {} for rid in self._replicas}
        self._routed_count = {rid: 0 for rid in self._replicas}
        self._total_requests = 0
        self._done_requests = 0
        self._unplaced = []             # drain resubmits whose
                                        # dispatch failed; pump retries
        self._pump_progressed = False
        self._last_refresh = None
        self.drain_events = []
        self.decisions = {k: 0 for k in _COUNTERS if k != 'reject'}
        self.rejects = 0
        # host-tier pages replicas reported warmed by advisory
        # prefetch hints (ISSUE 20) — cluster-side resurrect signal
        self.prefetch_warmed_pages = 0
        # per-tenant spill accounting (ISSUE 15): affinity placements
        # a tenant lost to backpressure — a heavy tenant saturating
        # its affinity replica shows up here, not in global spills
        self.tenant_spills = {}
        # goodput (ISSUE 17): prefix tokens drain-resubmits make peers
        # re-prefill — the cluster-level wasted-work cause no single
        # replica can see (each peer counts them as first-time work)
        self._drain_recompute_tokens = 0
        # telemetry time axis (ISSUE 18): the federated registry is
        # router-LOCAL (never the process-global one — in-process
        # LocalReplicas share that and would cross-contaminate), with
        # a history ring over it and the cluster-scope alert pack
        # evaluating on every history tick. Alert gauges/counters
        # still land in the GLOBAL registry (AlertManager default) so
        # health_dump / bench see them without a federated scrape.
        self._federated = _m.MetricsRegistry()
        self.history = self._federated.enable_history(
            capacity=history_capacity, clock=self._clock)
        self.alerts = AlertManager(
            self.history,
            rules=(alert_rules if alert_rules is not None
                   else router_rules()),
            clock=self._clock, source='router', report_dir=report_dir)

    OPTIMISTIC_GENERATIONS = 2

    # -- bookkeeping ---------------------------------------------------------
    def _count(self, kind):
        name, help_ = _COUNTERS[kind]
        _m.counter(name, help=help_).inc()
        if kind == 'reject':
            self.rejects += 1
        else:
            self.decisions[kind] = self.decisions.get(kind, 0) + 1

    def healthy_replicas(self):
        return [rid for rid in self._replicas
                if rid not in self._drained and rid not in self._hung]

    def _queue_depth(self, rid):
        # the replica's own view vs the router's dispatch record —
        # whichever is larger (a just-routed burst may not be in the
        # last status yet; a drained request may not be out of it)
        st = self._status.get(rid) or {}
        routed = sum(1 for r in self._by_replica[rid].values()
                     if not r.done)
        return max(st.get('waiting', 0) + st.get('in_flight', 0),
                   routed)

    def _load_key(self, rid):
        st = self._status.get(rid) or {}
        tl = st.get('timeline') or {}
        return (self._queue_depth(rid),
                tl.get('mean_occupancy', 0.0), str(rid))

    def _over_bound(self, rid):
        if self._queue_depth(rid) >= self.max_queue:
            return True
        if self.deadline_bound_s is not None:
            st = self._status.get(rid) or {}
            rate = st.get('decode_tokens_per_sec') or 0.0
            if rate > 0.0:
                pending = st.get('pending_tokens', 0)
                if pending / rate > self.deadline_bound_s:
                    return True
        return False

    def _retry_hint(self):
        """The structured RouterRejected back-off hint: for each
        healthy replica, pending_tokens / observed decode rate is its
        backlog's drain time, and one queue slot frees after roughly
        backlog / queue_depth of it — take the fastest replica's
        estimate. None on a cold cluster (no decode rate observed
        yet); the bench leg records how accurate this is against the
        actually-measured wait."""
        best = None
        for rid in self.healthy_replicas():
            st = self._status.get(rid) or {}
            rate = st.get('decode_tokens_per_sec') or 0.0
            if rate <= 0.0:
                continue
            depth = max(self._queue_depth(rid), 1)
            t = st.get('pending_tokens', 0) / rate / depth
            best = t if best is None else min(best, t)
        return best

    # -- placement -----------------------------------------------------------
    def _affinity_depth(self, hashes, rid):
        digest = self._digest.get(rid) or ()
        opt = self._optimistic.get(rid) or ()
        depth = 0
        for h in hashes:
            if h not in digest and h not in opt:
                break
            depth += 1
        return depth

    def place(self, prompt, count_reject=True, _hashes=None):
        """(decision, replica_id) for a prompt — affinity first,
        least-occupancy fallback, spill under backpressure, reject
        when everyone is saturated. `_hashes` lets submit() reuse the
        chain hashes it computes anyway for the digest update (one
        blake2b pass per prompt, not two)."""
        healthy = self.healthy_replicas()
        if not healthy:
            if count_reject:
                self._count('reject')
            raise RouterRejected("no healthy replicas",
                                 reason='no_healthy_replicas')
        hashes = _hashes if _hashes is not None else chain_hashes(
            prompt, self.page_size, limit=len(prompt) - 1)
        depths = {rid: self._affinity_depth(hashes, rid)
                  for rid in healthy}
        open_replicas = [r for r in healthy if not self._over_bound(r)]
        if not open_replicas:
            if count_reject:
                self._count('reject')
            hint = self._retry_hint()
            raise RouterRejected(
                f"all {len(healthy)} replicas over the backpressure "
                f"bound (max_queue={self.max_queue}"
                + (f", deadline_bound_s={self.deadline_bound_s}"
                   if self.deadline_bound_s is not None else '')
                + (f"; retry in ~{hint:.3f}s" if hint is not None
                   else '') + ")",
                reason='backpressure', retry_after_s=hint)
        maxdepth = max(depths.values())
        if maxdepth > 0:
            # deepest shared prefix wins; ties go to the lighter one
            best = min((r for r in healthy if depths[r] == maxdepth),
                       key=self._load_key)
            if best in open_replicas:
                return 'affinity', best
            # affinity target saturated: spill to the best OPEN
            # replica — deepest remaining prefix first (a partial
            # prefix hit still beats re-prefilling everything), load
            # as the tiebreak
            return 'spill', min(
                open_replicas,
                key=lambda r: (-depths[r],) + self._load_key(r))
        return 'least_loaded', min(open_replicas, key=self._load_key)

    def submit(self, prompt, **opts):
        """Place + submit one request; returns the RoutedRequest (or
        raises RouterRejected). Refreshes stale replica status first
        so placement never runs on a dead signal.

        Tenancy flows THROUGH the router (ISSUE 15): tenant_id /
        priority / deadline_s ride in `opts` to the replica's engine
        untouched. An engine-side deadline rejection (AdmissionRejected
        — the replica is healthy, the deadline just can't be met)
        re-raises as a structured RouterRejected carrying the engine's
        own retry hint, WITHOUT draining the replica."""
        self.refresh(max_age_s=self.refresh_interval_s)
        hashes = chain_hashes(prompt, self.page_size,
                              limit=len(prompt) - 1)
        req = RoutedRequest(prompt, opts)
        req.submit_t = self._clock()
        while True:
            decision, rid = self.place(prompt, _hashes=hashes)
            try:
                self._dispatch(req, rid, decision, hashes=hashes)
            except AdmissionRejected as e:
                self._count('reject')
                raise RouterRejected(
                    f"replica {rid} rejected at admission: {e}",
                    reason=e.reason,
                    retry_after_s=e.retry_after_s) from e
            except Exception as e:          # noqa: BLE001
                # the chosen replica died between refresh and
                # dispatch: drain it (its other in-flight requests
                # move too) and re-place — place() raises
                # RouterRejected once nobody healthy remains, with
                # nothing of THIS request stranded anywhere
                self._hung.add(rid)
                self.drain(rid, reason=f'submit dispatch failed: '
                                       f'{repr(e)[:120]}')
                continue
            self._count(decision)
            if decision == 'spill':
                tid = opts.get('tenant_id')
                if tid is not None:
                    self.tenant_spills[str(tid)] = \
                        self.tenant_spills.get(str(tid), 0) + 1
            return req

    def _dispatch(self, req, rid, decision, hashes=None):
        replica = self._replicas[rid]
        prompt = req.prompt + req.tokens        # resubmit = resurrect
        req._dispatch_base = len(req.tokens)
        if decision == 'affinity':
            # advisory host-tier prefetch hint (ISSUE 20): the replica
            # holds this prefix in its radix index — some of it may
            # have spilled to host RAM, so warm it back onto device
            # BEFORE the request lands. Best-effort by construction: a
            # tierless replica warms 0 pages, a channel hiccup must
            # not fail the placement (the submit path is authoritative
            # and resurrects on its own if the hint was lost).
            try:
                reply = replica.prefetch(prompt)
                self._count('prefetch_hint')
                if (reply or {}).get('warmed_pages'):
                    self.prefetch_warmed_pages += int(
                        reply['warmed_pages'])
            except Exception:               # noqa: BLE001
                pass
        opts = dict(req.opts)
        opts['max_new_tokens'] = req.budget_left
        remote = replica.submit(prompt, opts, route_meta={
            'replica_id': str(rid), 'router_decision': decision})
        req.replica_id, req.remote_rid = rid, remote
        req.decision = decision if req.decision is None else req.decision
        self._open[req.id] = req
        self._by_replica[rid][remote] = req
        self._routed_count[rid] += 1
        self._total_requests += 1 if req.resubmits == 0 else 0
        # optimistic digest overlay: the pages this prompt will index
        # land on rid — siblings submitted before the replica indexes
        # and publishes them still route there (aged out after
        # OPTIMISTIC_GENERATIONS refreshes; the published digest is
        # the durable signal)
        if hashes is None:
            hashes = chain_hashes(prompt, self.page_size,
                                  limit=len(prompt) - 1)
        gen = self._refresh_gen[rid]
        self._optimistic[rid].update(dict.fromkeys(hashes, gen))

    # -- metrics federation (ISSUE 18) ---------------------------------------
    def _fed_gauge(self, name, help=''):
        return self._federated.gauge(name, help=help,
                                     labelnames=('replica',))

    def _feed_federated(self, rid, st, tl):
        """One replica's status into the federated registry — the
        history/alert substrate. Single-engine ptpu_serve_* names keep
        their meaning, one series per replica under the `replica`
        label; staleness stamps on these series are how a quiet
        replica shows in the cluster scrape."""
        r = str(rid)
        fg = self._fed_gauge
        beat = st.get('beat_age_s')
        if beat is not None:
            help_ = ('replica step-loop heartbeat age at the last '
                     'router refresh (replica_heartbeat_stale input)')
            fg('ptpu_cluster_replica_beat_age_seconds',
               help=help_).set(beat, replica=r)
            _m.gauge('ptpu_cluster_replica_beat_age_seconds',
                     help=help_, labelnames=('replica',)).set(
                beat, replica=r)
        fg('ptpu_cluster_replica_queue_depth',
           help='per-replica waiting + in-flight (router view)').set(
            self._queue_depth(rid), replica=r)
        fg('ptpu_cluster_replica_occupancy',
           help='per-replica mean decode-slot occupancy').set(
            tl.get('mean_occupancy') or 0.0, replica=r)
        pool = st.get('pool') or {}
        if pool.get('num_pages'):
            fg('ptpu_serve_kv_page_utilization',
               help='KV pool pages in use / total').set(
                pool.get('pages_in_use', 0) / pool['num_pages'],
                replica=r)
        fg('ptpu_serve_requests_waiting', help='queued requests').set(
            st.get('waiting', 0), replica=r)
        fg('ptpu_serve_requests_in_flight',
           help='requests holding a decode slot').set(
            st.get('in_flight', 0), replica=r)
        fg('ptpu_serve_decode_tokens_per_sec',
           help='batched decode throughput (tokens/sec)').set(
            st.get('decode_tokens_per_sec') or 0.0, replica=r)
        fg('ptpu_serve_degrade_stage',
           help='graceful-degradation ladder stage').set(
            st.get('degrade_stage', 0) or 0, replica=r)
        gf = (st.get('goodput') or {}).get('goodput_fraction')
        if gf is not None:
            fg('ptpu_serve_goodput_fraction',
               help='delivered / emitted tokens').set(gf, replica=r)

    def _feed_router_counters(self):
        """Router-scope lifetime counts as unlabeled federated gauges
        — the substrate the drain/resubmit-storm and spill-rate delta
        rules window over."""
        for kind in ('drain', 'resubmit', 'spill', 'reject'):
            name, help_ = _COUNTERS[kind]
            val = (self.rejects if kind == 'reject'
                   else self.decisions.get(kind, 0))
            self._federated.gauge(name, help=help_).set(val)

    def federate(self):
        """Pull each live replica's compact `metrics` snapshot (the
        channel op — engine-truth scalars, not the shared global
        registry) and merge it under the `replica` label; ticks the
        cluster history. Returns {replica_id: reply}."""
        out = {}
        for rid, replica in self._replicas.items():
            if rid in self._drained:
                continue
            try:
                m = replica.metrics()
            except Exception:               # noqa: BLE001
                continue                    # pre-ISSUE-18 worker
            out[str(rid)] = m
            for name, val in sorted((m.get('series') or {}).items()):
                if val is None:
                    continue
                self._fed_gauge(name).set(float(val), replica=str(rid))
        self.history.tick()
        return out

    def cluster_prometheus_text(self, federate=True):
        """ONE scrape for the whole cluster: merge fresh per-replica
        snapshots, then render the federated registry with per-series
        staleness ages (a dead replica's series visibly age out)."""
        if federate:
            self.federate()
        return self._federated.prometheus_text(include_age=True)

    def serve_metrics_http(self, port=0, addr='127.0.0.1'):
        """Embeddable cluster-wide /metrics endpoint over the
        federated registry (GET /metrics, /metrics.json). The scrape
        renders the LAST federated state — keep it fresh by calling
        refresh()/federate() from the serving loop, which run() and
        serve() already do."""
        return _m.MetricsServer(port=port, addr=addr,
                                registry=self._federated)

    # -- health / status -----------------------------------------------------
    def refresh(self, max_age_s=0.0):
        """Pull status from every live replica (digest, queue depth,
        timeline summary, heartbeat). An unresponsive or self-reported
        hung replica is drained."""
        now = self._clock()
        if (self._last_refresh is not None
                and now - self._last_refresh < max_age_s):
            return
        self._last_refresh = now
        for rid, replica in list(self._replicas.items()):
            if rid in self._drained:
                continue
            try:
                st = replica.status()
            except Exception as e:          # noqa: BLE001
                self._hung.add(rid)
                self.drain(rid, reason=f'status unreachable: '
                                       f'{repr(e)[:120]}')
                continue
            self._status[rid] = st
            tl = st.get('timeline') or {}
            _m.gauge('ptpu_cluster_replica_queue_depth',
                     help='per-replica waiting + in-flight requests '
                          '(router view)',
                     labelnames=('replica',)).set(
                self._queue_depth(rid), replica=str(rid))
            _m.gauge('ptpu_cluster_replica_occupancy',
                     help='per-replica mean decode-slot occupancy '
                          '(SchedulerTimeline window)',
                     labelnames=('replica',)).set(
                tl.get('mean_occupancy') or 0.0, replica=str(rid))
            self._feed_federated(rid, st, tl)
            digest = st.get('prefix_digest')
            if digest is not None:
                # REPLACE with what the replica actually holds — a
                # union would keep routing to pages the pool LRU
                # evicted long ago. Optimistic entries live in their
                # own overlay and age out by refresh generation.
                self._digest[rid] = {int(h) for h in digest}
                gen = self._refresh_gen[rid] = \
                    self._refresh_gen[rid] + 1
                horizon = gen - self.OPTIMISTIC_GENERATIONS
                self._optimistic[rid] = {
                    h: g for h, g in self._optimistic[rid].items()
                    if g > horizon}
            if st.get('hung') or (
                    st.get('beat_age_s') is not None
                    and st['beat_age_s'] > self.hang_timeout_s):
                self._hung.add(rid)
                self.drain(rid, reason=st.get(
                    'hang_reason') or
                    f"heartbeat stale {st.get('beat_age_s'):.1f}s")
        # history sample + alert evaluation ride the refresh cadence
        # (metadata-only; deterministic under an injected clock)
        self._feed_router_counters()
        self.history.tick()

    def drain(self, rid, reason='operator drain'):
        """Stop placement on `rid` and move its in-flight requests to
        peers. Safe on an unresponsive replica: the router's own
        records say what was running there and how many tokens each
        request already streamed back."""
        if rid in self._drained:
            return []
        self._drained.add(rid)
        self._count('drain')
        event = {'replica_id': str(rid), 'reason': reason,
                 't': self._clock(), 'resubmitted': 0}
        self.drain_events.append(event)
        # best-effort remote snapshot: a replica whose STEP loop is
        # wedged still answers on the control thread and reports
        # tokens the router's poll may not have seen yet
        snapshots = {}
        try:
            for snap in self._replicas[rid].drain():
                snapshots[snap['rid']] = snap
        except Exception:                   # noqa: BLE001
            pass
        moved = []
        for remote, req in list(self._by_replica[rid].items()):
            if req.done:
                continue
            snap = snapshots.get(remote)
            if snap is not None:
                self._merge_tokens(req, snap.get('generated', ()))
            self._finish_if_done(req)
            if req.done:
                continue
            req.resubmits += 1
            self._count('resubmit')
            if self._resubmit(req):
                moved.append(req)
        self._by_replica[rid] = {}
        event['resubmitted'] = len(moved)
        return moved

    def _resubmit(self, req):
        """Re-place one drained request on a peer. Never raises: a
        failed dispatch (peer channel hiccup, peer itself draining,
        nobody healthy right now) parks the request in `_unplaced`
        and pump() keeps retrying — a drain must move EVERY request
        it can and strand none on a transient error."""
        try:
            try:
                decision, peer = self.place(req.prompt + req.tokens,
                                            count_reject=False)
            except RouterRejected:
                # drained work is NOT new admission — it was already
                # accepted once and must land somewhere. Bypass the
                # backpressure bound onto the least-loaded healthy
                # peer (reject-early guards the front door, not
                # requests mid-flight).
                healthy = self.healthy_replicas()
                if not healthy:
                    raise
                decision = 'spill'
                peer = min(healthy, key=self._load_key)
            self._dispatch(req, peer, decision)
            # goodput (ISSUE 17): the peer re-prefills the whole
            # resubmitted prefix (prompt + tokens streamed so far) —
            # work the cluster already paid for once. Priced here
            # because only the router sees the resubmit; the peer's
            # own ledger counts those positions as first-time
            # delivered, and cluster_snapshot() moves this many from
            # delivered to wasted. Upper bound: a peer prefix-cache
            # hit shrinks the actual recompute.
            recompute = len(req.prompt) + len(req.tokens)
            self._drain_recompute_tokens += recompute
            _m.counter(
                'ptpu_route_drain_recompute_tokens_total',
                help='drain-resubmit recompute: prefix tokens peers '
                     're-prefill for requests moved off a drained '
                     'replica (lifetime; priced as wasted in '
                     'cluster_snapshot goodput)').inc(recompute)
            return True
        except Exception:                   # noqa: BLE001
            if req not in self._unplaced:
                self._unplaced.append(req)
            return False

    @staticmethod
    def _merge_tokens(req, generated):
        """Fold a replica's reported continuation into the routed
        request: the replica only knows tokens since ITS dispatch, so
        they append after the pre-dispatch prefix."""
        if len(generated) > len(req.tokens) - req._dispatch_base:
            req.tokens = (req.tokens[:req._dispatch_base]
                          + [int(t) for t in generated])

    def _mark_done(self, req):
        """Terminal bookkeeping: prune from the open/by-replica maps
        (the router is long-lived — done requests must not accumulate)
        and keep the request in the capped recent ring for the SLO
        view. The caller's own RoutedRequest reference stays valid."""
        req.done = True
        if req.finish_t is None:
            req.finish_t = self._clock()
        if self._open.pop(req.id, None) is not None:
            self._done_requests += 1
            self._recent.append(req)
        by = self._by_replica.get(req.replica_id)
        if by is not None:
            by.pop(req.remote_rid, None)

    def _finish_if_done(self, req):
        eos = req.opts.get('eos_token_id')
        if req.budget_left <= 0 or (
                eos is not None and req.tokens
                and req.tokens[-1] == eos):
            self._mark_done(req)

    # -- progress ------------------------------------------------------------
    def pump(self):
        """Drive in-process replicas one engine step and fold every
        replica's poll into the routed requests. Returns True while
        anything is still in flight."""
        live = False
        self._pump_progressed = False
        for req in list(self._unplaced):    # drain leftovers retry
            if req.done or self._resubmit(req):
                self._unplaced.remove(req)
        for rid, replica in self._replicas.items():
            if rid in self._drained:
                continue
            try:
                if replica.pump():
                    self._pump_progressed = True
                polled = replica.poll()
            except Exception as e:          # noqa: BLE001
                self.drain(rid, reason=f'poll failed: {repr(e)[:120]}')
                continue
            for remote, view in polled.items():
                req = self._by_replica[rid].get(remote)
                if req is None or req.done:
                    continue
                before = len(req.tokens)
                self._merge_tokens(req, view.get('generated', ()))
                if len(req.tokens) != before:
                    self._pump_progressed = True
                if view.get('done'):
                    self._mark_done(req)
                    self._pump_progressed = True
                else:
                    live = True
        return live or bool(self._open) or bool(self._unplaced)

    def run(self, timeout_s=120.0, poll_interval_s=None):
        """Pump until every routed request finishes (health-checked
        every refresh_interval_s). A pass that neither stepped a local
        replica nor saw new tokens backs off `poll_interval_s`
        (default 5ms) instead of hot-looping TCP polls against worker
        control threads that are busy decoding."""
        if poll_interval_s is None:
            poll_interval_s = 0.005
        t0 = self._clock()
        while self._open or self._unplaced:
            self.refresh(max_age_s=self.refresh_interval_s)
            self.pump()
            if self._clock() - t0 > timeout_s:
                raise RuntimeError(
                    f"cluster did not drain in {timeout_s}s "
                    f"(open: {sorted(self._open)})")
            if poll_interval_s and not self._pump_progressed:
                time.sleep(poll_interval_s)
        self.refresh(max_age_s=0.0)     # snapshot() sees final state
        return list(self._recent)

    def serve(self, prompts, timeout_s=120.0, **opts):
        """Submit a prompt list, run to completion, return outputs in
        submission order — the cluster-wide `engine.generate`.

        Unlike raw `submit()` (the reject-early surface for callers
        who can retry), serve() THROTTLES on RouterRejected: it pumps
        the replicas and retries, backing off by the rejection's OWN
        `retry_after_s` hint (ISSUE 15 — pump until the hinted window
        elapses, then re-place) instead of hammering resubmits every
        pump; a hint-less rejection retries after one pump as before.
        A rejection with no progress possible (no healthy replicas)
        still escapes via the timeout."""
        t0 = self._clock()
        reqs = []
        for p in prompts:
            while True:
                try:
                    reqs.append(self.submit(p, **opts))
                    break
                except RouterRejected as rej:
                    if self._clock() - t0 > timeout_s:
                        raise
                    self._backoff(rej.retry_after_s,
                                  deadline=t0 + timeout_s)
        self.run(timeout_s=max(timeout_s - (self._clock() - t0), 1.0))
        return [r.output_ids() for r in reqs]

    def _backoff(self, retry_after_s, deadline):
        """Pump the cluster through a rejection's back-off window:
        local replicas keep stepping (their queues ARE the reason for
        the rejection), remote ones get polled, and an unproductive
        pass sleeps briefly instead of hot-looping the control plane.
        Returns once the hinted window elapses (one pump minimum) or
        the caller's deadline arrives."""
        t0 = self._clock()
        while True:
            self.refresh(max_age_s=self.refresh_interval_s)
            self.pump()
            now = self._clock()
            if retry_after_s is None or now - t0 >= retry_after_s \
                    or now >= deadline:
                return
            if not self._pump_progressed:
                time.sleep(min(retry_after_s, 0.005))

    # -- views ---------------------------------------------------------------
    def snapshot(self):
        """JSON-ready router view: placement counters, per-replica
        load/digest sizes, drain events — what `tools/health_dump.py
        cluster` renders and the bench leg records."""
        per_replica = {}
        for rid in self._replicas:
            st = self._status.get(rid) or {}
            tl = st.get('timeline') or {}
            per_replica[str(rid)] = {
                'drained': rid in self._drained,
                'hung': rid in self._hung or bool(st.get('hung')),
                'queue_depth': self._queue_depth(rid),
                'waiting': st.get('waiting', 0),
                'in_flight': st.get('in_flight', 0),
                'mean_occupancy': tl.get('mean_occupancy'),
                'decode_tokens': tl.get('decode_tokens'),
                'prefill_tokens': tl.get('prefill_tokens'),
                'preemptions': tl.get('preemptions'),
                'degrade_stage': st.get('degrade_stage', 0),
                'digest_size': len(self._digest.get(rid) or ())
                + len(self._optimistic.get(rid) or ()),
                'requests_routed': self._routed_count[rid],
                'goodput': st.get('goodput'),
            }
        total = sum(self.decisions.get(k, 0)
                    for k in ('affinity', 'least_loaded', 'spill'))
        return {
            'replicas': per_replica,
            'placements': dict(self.decisions),
            'rejects': self.rejects,
            'prefetch_warmed_pages': self.prefetch_warmed_pages,
            'affinity_hit_rate':
                (self.decisions.get('affinity', 0) / total
                 if total else None),
            'drain_events': list(self.drain_events),
            'requests': self._total_requests,
            'requests_done': self._done_requests,
            'tenant_spills': dict(self.tenant_spills),
            'goodput': self._cluster_goodput(per_replica),
            'tenants': self._cluster_tenants(),
            'alerts': self.alerts.summary(),
        }

    def cluster_snapshot(self):
        """The full cluster view (ISSUE 18 name): snapshot() including
        the cluster-wide per-tenant table and the alert summary."""
        return self.snapshot()

    _TENANT_SUM_KEYS = ('submitted', 'completed', 'aborted',
                        'quota_deferrals', 'preemptions_charged',
                        'charge_tokens', 'deadline_rejects',
                        'deadline_misses', 'tokens_billed')

    def _cluster_tenants(self):
        """Cluster-wide per-tenant accounting: each tenant's rows
        summed across the replicas' last-published tenancy tables —
        the per-replica-bucket N x-quota effect made measurable before
        quota sharing ships (ISSUE 18 observe-only half) — plus the
        router's own per-tenant spill counts."""
        out = {}
        for rid in self._replicas:
            st = self._status.get(rid) or {}
            rows = (st.get('tenancy') or {}).get('tenants') or {}
            for tid, row in rows.items():
                dst = out.setdefault(str(tid), {'replicas': 0})
                dst['replicas'] += 1
                for k in self._TENANT_SUM_KEYS:
                    v = row.get(k)
                    if v is not None:
                        dst[k] = dst.get(k, 0) + v
        for tid, n in self.tenant_spills.items():
            out.setdefault(str(tid),
                           {'replicas': 0})['router_spills'] = n
        return out

    def _cluster_goodput(self, per_replica):
        """Aggregate the replicas' goodput accounts and reprice the
        drain-resubmit recompute: each peer counted a resubmitted
        prefix as first-time delivered work, so the router MOVES those
        tokens delivered -> wasted (cause drain_recompute), keeping
        delivered + wasted == emitted exact at the cluster level. None
        until some replica reports a goodput block (pre-ISSUE-17
        workers)."""
        agg = {'emitted_tokens': 0, 'delivered_tokens': 0,
               'wasted_tokens': 0, 'wasted_by_cause': {},
               'spec_shed_tokens': 0}
        seen = False
        for row in per_replica.values():
            g = row.get('goodput')
            if not g:
                continue
            seen = True
            for k in ('emitted_tokens', 'delivered_tokens',
                      'wasted_tokens', 'spec_shed_tokens'):
                agg[k] += int(g.get(k, 0) or 0)
            for c, v in (g.get('wasted_by_cause') or {}).items():
                agg['wasted_by_cause'][c] = \
                    agg['wasted_by_cause'].get(c, 0) + int(v)
        if not seen:
            return None
        moved = min(self._drain_recompute_tokens,
                    agg['delivered_tokens'])
        agg['delivered_tokens'] -= moved
        agg['wasted_tokens'] += moved
        agg['wasted_by_cause']['drain_recompute'] = \
            agg['wasted_by_cause'].get('drain_recompute', 0) + moved
        agg['drain_recompute_tokens'] = self._drain_recompute_tokens
        agg['goodput_fraction'] = (
            agg['delivered_tokens'] / agg['emitted_tokens']
            if agg['emitted_tokens'] else None)
        return agg

    def request_slo(self):
        """Router-side per-request latency view (submit→finish as the
        ROUTER saw it — includes channel + drain resubmission time the
        engine-side traces can't see). Open requests plus the capped
        ring of recently finished ones."""
        out = {}
        for r in list(self._recent) + list(self._open.values()):
            out[r.id] = {
                'req': r.id, 'replica_id': str(r.replica_id),
                'router_decision': r.decision,
                'resubmits': r.resubmits,
                'tokens_generated': len(r.tokens),
                'e2e_s': (r.finish_t - r.submit_t)
                if r.done and r.submit_t is not None else None,
            }
        return out

    def shutdown(self):
        for replica in self._replicas.values():
            try:
                replica.shutdown()
            except Exception:               # noqa: BLE001
                pass


def cluster_snapshot():
    """The ptpu_route_* counters currently in the monitor registry
    (None-able mirror of the last router's activity) — the
    StepTelemetry / health_dump pickup point."""
    reg = _m.metrics()
    out = {}
    for kind, (name, _h) in _COUNTERS.items():
        m = reg.get(name)
        if m is not None:
            out[name] = m.value()
    m = reg.get('ptpu_route_drain_recompute_tokens_total')
    if m is not None:
        out['ptpu_route_drain_recompute_tokens_total'] = m.value()
    return out or None
