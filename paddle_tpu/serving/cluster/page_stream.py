"""KV-page streaming between pools (prefill→decode disaggregation).

Copies whole page rows from a source pool's device arrays into chosen
pages of a destination pool — the handoff of arXiv:2112.01075's
portable collective-based redistribution applied to KV pages: the
transfer is expressed as gather→scatter on the page axis, chunked so
the staging footprint is bounded (`core.bucketing._chunk_spans`, the
same chunking the PR-10 chunked collectives use). On one host this
lowers to a device copy; across device slices XLA lowers the same
expression to an ICI transfer. Int8 pools need no special casing:
each layer's buffer TUPLE is streamed element-wise, so the fp32 scale
siblings travel with their int8 pages (same page ids address both —
kv_pool.py docstring).

Bit-exactness is the contract (tested in test_serving_cluster.py):
a streamed page equals the locally-written page byte for byte,
because nothing is recomputed or re-quantized — rows move as stored.
"""
from ...core import monitor as _m
from ...core.bucketing import _chunk_spans


def stream_kv_pages(src_kv, dst_kv, src_pages, dst_pages,
                    chunk_pages=0):
    """Copy page rows `src_pages[i] -> dst_pages[i]` for every layer
    buffer. Returns the NEW dst_kv list (functional — callers assign
    it back to their pool, like the engine does with step outputs).

    chunk_pages caps pages moved per copy op (0 = one shot)."""
    import jax.numpy as jnp
    if len(src_pages) != len(dst_pages):
        raise ValueError(f"page list mismatch: {len(src_pages)} src "
                         f"vs {len(dst_pages)} dst")
    n = len(src_pages)
    if n == 0:
        return dst_kv
    spans = _chunk_spans(n, 1, chunk_pages) or [(0, n)]
    src_idx = jnp.asarray(list(src_pages), jnp.int32)
    dst_idx = jnp.asarray(list(dst_pages), jnp.int32)
    out = []
    nbytes = 0
    for layer_src, layer_dst in zip(src_kv, dst_kv):
        bufs = []
        for s, d in zip(layer_src, layer_dst):
            for (st, w) in spans:
                d = d.at[dst_idx[st:st + w]].set(s[src_idx[st:st + w]])
            nbytes += n * int(s.nbytes) // s.shape[0]
            bufs.append(d)
        out.append(tuple(bufs))
    _m.counter('ptpu_serve_pd_streamed_pages_total',
               help='KV pages streamed prefill->decode '
                    '(lifetime)').inc(n)
    _m.counter('ptpu_serve_pd_streamed_bytes_total',
               help='device bytes streamed prefill->decode, scale '
                    'buffers included (lifetime)').inc(nbytes)
    return out
