"""Length-prefixed JSON control channel between router and replicas.

The cluster's CONTROL plane only: submissions, token polls, status
probes, drains, and the `metrics` federation op (a compact
per-replica series snapshot the router merges into its cluster
registry — ISSUE 18). Token ids are small JSON ints; the DATA plane (KV
pages) never crosses this socket — pages move device-to-device via
page_stream.py. One request per message, strictly ordered per
connection; the client serializes calls under a lock, so a replica
can serve several routers (or a router several probes) without
interleaving frames.

Deliberately dependency-free (stdlib sockets): the fleetrun TCPStore
is a rendezvous KV, not an RPC duplex, and serving control needs
request/response with per-call timeouts — a stale-status timeout is
the router's hang signal (router.py), so timeouts must be cheap and
per-call.
"""
import json
import socket
import struct
import threading

_HDR = struct.Struct('<I')
MAX_MSG = 64 * 1024 * 1024


def send_msg(sock, obj):
    data = json.dumps(obj).encode()
    if len(data) > MAX_MSG:
        raise ValueError(f"control message of {len(data)} bytes "
                         f"exceeds the {MAX_MSG} cap")
    sock.sendall(_HDR.pack(len(data)) + data)


def _recv_exact(sock, n):
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("control channel closed")
        buf += chunk
    return buf


def recv_msg(sock):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if n > MAX_MSG:
        raise ValueError(f"control frame of {n} bytes exceeds cap")
    return json.loads(_recv_exact(sock, n).decode())


class ControlServer:
    """Accept-loop + per-connection handler threads. `handler(msg)`
    returns the reply dict; exceptions become {'error': repr} replies
    so a bad request can't kill the worker's control plane."""

    def __init__(self, handler, host='127.0.0.1', port=0):
        self.handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._accept_loop,
                                        name='cluster-control',
                                        daemon=True)
        self._thread.start()
        return self

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        with conn:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                try:
                    reply = self.handler(msg) or {}
                except Exception as e:          # noqa: BLE001
                    reply = {'error': repr(e)[:500]}
                try:
                    send_msg(conn, reply)
                except OSError:
                    return

    def close(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class ControlClient:
    """One persistent connection; `call()` is request/response with a
    per-call timeout (socket.timeout propagates — the router reads it
    as 'replica unresponsive').

    Frames carry no request ids, so a connection that failed MID-CALL
    is desynced: a late reply to the timed-out request would be read
    as the NEXT call's reply. Any send/recv failure therefore drops
    the connection; the next call dials fresh (the server's stale
    per-connection thread dies writing to the closed socket)."""

    def __init__(self, host, port, timeout=10.0):
        self._addr = (host, int(port))
        self._lock = threading.Lock()
        self._timeout = timeout
        self._sock = socket.create_connection(self._addr,
                                              timeout=timeout)

    def call(self, msg, timeout=None):
        with self._lock:
            if self._sock is None:
                self._sock = socket.create_connection(
                    self._addr, timeout=timeout or self._timeout)
            try:
                self._sock.settimeout(timeout or self._timeout)
                send_msg(self._sock, msg)
                reply = recv_msg(self._sock)
            except (OSError, ValueError, ConnectionError):
                # desynced or dead: never reuse this connection
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise
        if isinstance(reply, dict) and reply.get('error'):
            raise RuntimeError(f"replica error: {reply['error']}")
        return reply

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
