"""Disaggregated multi-chip serving cluster (ISSUE 11).

The PR-5/9 engine scaled one device slice; this package scales the
mesh (ROADMAP serve_scale item 1):

  * `replica.py`  — dp serving replicas: each runs the existing
    engine over its own device slice, either in-process
    (`LocalReplica`) or as a fleet-launched worker process
    (`ReplicaWorker` + `RemoteReplica`) behind a TCP control channel
    (submit / poll / abort / drain / status), with a PR-2-style hang
    watchdog that diagnoses and dumps a wedged step loop;
  * `router.py`   — the async front-end: prefix-affinity placement
    (radix-chain hashes vs each replica's published prefix digest),
    least-occupancy fallback on the PR-6 SchedulerTimeline feedback,
    per-replica backpressure + reject-early, and drain (a hung
    replica's in-flight requests re-prefill on a peer via the PR-9
    resurrect path); plus metrics federation (ISSUE 18): one
    cluster-wide scrape over a router-local registry fed by the
    replicas' `metrics` channel op, with history rings and the
    cluster-scope alert pack (core/alerts.router_rules) on top;
  * mp sharding   — `ServingEngine(..., mesh=...)` (engine.py) splits
    heads + KV pages over an 'mp' axis inside one replica;
  * `disagg.py`   — prefill/decode disaggregation behind a config
    flag: chunked prefill on a dedicated engine, finished KV pages
    streamed into the decode engine's pool (`page_stream.py`, int8
    scale buffers ride along) and the request adopted into a decode
    slot.

docs/serving.md#disaggregated-serving has the topology diagram, knob
tables and drain semantics.
"""
from .router import (ClusterRouter, RouterRejected, RoutedRequest,
                     cluster_snapshot)
from .replica import LocalReplica, RemoteReplica, ReplicaWorker
from .disagg import DisaggregatedEngine, build_engine
from .page_stream import stream_kv_pages

# the router's descriptive name (ISSUE 15 forwards tenancy through
# it); ClusterRouter remains the historical alias
PrefixAffinityRouter = ClusterRouter

__all__ = ['ClusterRouter', 'PrefixAffinityRouter', 'RouterRejected',
           'RoutedRequest', 'cluster_snapshot', 'LocalReplica',
           'RemoteReplica', 'ReplicaWorker', 'DisaggregatedEngine',
           'build_engine', 'stream_kv_pages']
