"""Block-paged KV-cache pool (vLLM-style paging, TPU-shaped).

One fixed device tensor pair per decoder layer — `[num_pages,
page_size, local_heads * head_dim]` — shared by every in-flight
request. Sequences own pages through per-sequence page tables; a
host-side free-list allocator hands pages out and takes them back, so
KV memory is O(pages actually in use) instead of the dense cache's
O(batch * max_seq_len). The ragged paged-attention kernel gathers a
row's pages straight from this layout (`ops/pallas/paged_attention.py`
module docstring has the exact shapes).

The allocator is deliberately host-side and dumb-simple: serving
decisions (admit / grow / preempt) happen between jitted steps, where
Python cost is amortized over a whole batch step. Invariants it
enforces (tested in tests/test_serving.py):

  * a page has exactly one owner (no double-mapping);
  * free + in-use partitions the pool at all times;
  * release returns every page of a sequence exactly once.
"""
import math
import threading


class PoolExhausted(RuntimeError):
    """No free pages — the scheduler's cue to stop admitting or to
    preempt a victim (engine.py)."""


class KVPagePool:
    """Free-list page allocator + the paged device arrays.

    Device arrays are created lazily (`materialize()`) so pure
    allocator tests never touch jax; the engine materializes once at
    build. `kv[l]` is the (k_pages, v_pages) pair of layer l.
    """

    def __init__(self, num_pages, page_size, num_layers=0, num_heads=0,
                 head_dim=0, dtype=None):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.kv = None                      # [(k_pages, v_pages)] per layer
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._owner = {}                    # page id -> seq id
        self._seq_pages = {}                # seq id -> [page ids]
        self._lock = threading.Lock()
        self.alloc_total = 0
        self.free_total = 0
        self.high_water = 0

    # -- device arrays -------------------------------------------------------
    def materialize(self):
        if self.kv is not None:
            return self.kv
        import jax.numpy as jnp
        dt = self.dtype or jnp.float32
        hd = self.num_heads * self.head_dim
        self.kv = [
            (jnp.zeros((self.num_pages, self.page_size, hd), dt),
             jnp.zeros((self.num_pages, self.page_size, hd), dt))
            for _ in range(self.num_layers)]
        return self.kv

    def drop_arrays(self):
        """Release the device buffers (engine shutdown)."""
        self.kv = None

    # -- allocator -----------------------------------------------------------
    def pages_for(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.page_size))

    @property
    def pages_in_use(self):
        return self.num_pages - len(self._free)

    @property
    def free_pages(self):
        return len(self._free)

    def utilization(self):
        return self.pages_in_use / self.num_pages

    def capacity_tokens(self, seq_id):
        """Tokens the sequence can hold without another allocation."""
        return len(self._seq_pages.get(seq_id, ())) * self.page_size

    def page_table(self, seq_id):
        return list(self._seq_pages.get(seq_id, ()))

    def owned_sequences(self):
        return list(self._seq_pages)

    def _take_page(self, seq_id):
        if not self._free:
            raise PoolExhausted(
                f"KV pool exhausted: {self.num_pages} pages of "
                f"{self.page_size} tokens all in use")
        page = self._free.pop()
        assert page not in self._owner, f"page {page} double-mapped"
        self._owner[page] = seq_id
        self._seq_pages.setdefault(seq_id, []).append(page)
        self.alloc_total += 1
        self.high_water = max(self.high_water, self.pages_in_use)
        return page

    def ensure_capacity(self, seq_id, n_tokens):
        """Grow seq_id's page list to hold n_tokens. Raises
        PoolExhausted (after rolling back nothing — partial growth is
        kept, the caller preempts and retries)."""
        need = self.pages_for(n_tokens)
        with self._lock:
            while len(self._seq_pages.get(seq_id, ())) < need:
                self._take_page(seq_id)
        return self._seq_pages[seq_id]

    def release(self, seq_id):
        """Return every page of seq_id to the free list."""
        with self._lock:
            pages = self._seq_pages.pop(seq_id, [])
            for page in pages:
                owner = self._owner.pop(page, None)
                assert owner == seq_id, \
                    f"page {page} owned by {owner}, freed by {seq_id}"
                self._free.append(page)
                self.free_total += 1
        return len(pages)

    def reset(self):
        with self._lock:
            self._free = list(range(self.num_pages - 1, -1, -1))
            self._owner.clear()
            self._seq_pages.clear()

    def census(self):
        """{seq_id: pages held} — who is sitting on the pool right now
        (the serve_report watchdog artifact embeds this so a stalled
        request's report names the page hogs)."""
        with self._lock:
            return {seq: len(pages)
                    for seq, pages in self._seq_pages.items()}

    def stats(self):
        return {
            'num_pages': self.num_pages,
            'page_size': self.page_size,
            'pages_in_use': self.pages_in_use,
            'free_pages': self.free_pages,
            'utilization': self.utilization(),
            'high_water': self.high_water,
            'alloc_total': self.alloc_total,
            'free_total': self.free_total,
            'sequences': len(self._seq_pages),
        }
