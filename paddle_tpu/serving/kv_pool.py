"""Block-paged KV-cache pool (vLLM-style paging, TPU-shaped).

One fixed device tensor pair per decoder layer — `[num_pages,
page_size, local_heads * head_dim]` — shared by every in-flight
request. Sequences own pages through per-sequence page tables; a
host-side free-list allocator hands pages out and takes them back, so
KV memory is O(pages actually in use) instead of the dense cache's
O(batch * max_seq_len). The ragged paged-attention kernel gathers a
row's pages straight from this layout (`ops/pallas/paged_attention.py`
module docstring has the exact shapes).

Quantized pages (`kv_dtype='int8'`, ISSUE 7): each layer's entry
becomes a 4-tuple `(k_pages int8, v_pages int8, k_scales fp32,
v_scales fp32)` with scales of shape `[num_pages, page_size,
local_heads]` — one abs-max scale per (token slot, head), computed
when the token's K/V row is scattered in (`write_kv_pages_quantized`)
so already-written slots never rescale. Attention dequantizes inside
the kernel (or the dense fallback), so the math stays fp32 while the
pool holds ~4x (vs fp32) / ~2x (vs bf16) more tokens per byte; the
exact per-token byte math is `bytes_per_token()` below and
docs/serving.md#quantized-kv.

The allocator is deliberately host-side and dumb-simple: serving
decisions (admit / grow / preempt) happen between jitted steps, where
Python cost is amortized over a whole batch step. Invariants it
enforces (tested in tests/test_serving.py):

  * a page has exactly one owner (no double-mapping);
  * free + in-use partitions the pool at all times;
  * release returns every page of a sequence exactly once.
"""
import math
import threading

import numpy as _np


def _np_dtype(dt):
    """np.dtype of a string / numpy / jnp dtype spec without importing
    jax for the common cases (pure-allocator tests stay jax-free)."""
    try:
        return _np.dtype(dt)
    except TypeError:
        import jax.numpy as jnp
        return _np.dtype(jnp.dtype(dt))


class PoolExhausted(RuntimeError):
    """No free pages — the scheduler's cue to stop admitting or to
    preempt a victim (engine.py)."""


class KVPagePool:
    """Free-list page allocator + the paged device arrays.

    Device arrays are created lazily (`materialize()`) so pure
    allocator tests never touch jax; the engine materializes once at
    build. `kv[l]` is the (k_pages, v_pages) pair of layer l.
    """

    def __init__(self, num_pages, page_size, num_layers=0, num_heads=0,
                 head_dim=0, dtype=None):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.kv = None                      # [(k_pages, v_pages)] per layer
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._owner = {}                    # page id -> seq id
        self._seq_pages = {}                # seq id -> [page ids]
        self._lock = threading.Lock()
        self.alloc_total = 0
        self.free_total = 0
        self.high_water = 0

    # -- device arrays -------------------------------------------------------
    @property
    def quantized(self):
        """True when pages store int8 + per-(slot, head) fp32 scales."""
        if self.dtype is None:
            return False
        return _np_dtype(self.dtype) == _np.int8

    def materialize(self):
        if self.kv is not None:
            return self.kv
        import jax.numpy as jnp
        hd = self.num_heads * self.head_dim
        if self.quantized:
            shape = (self.num_pages, self.page_size, hd)
            sshape = (self.num_pages, self.page_size, self.num_heads)
            self.kv = [
                (jnp.zeros(shape, jnp.int8),
                 jnp.zeros(shape, jnp.int8),
                 jnp.zeros(sshape, jnp.float32),
                 jnp.zeros(sshape, jnp.float32))
                for _ in range(self.num_layers)]
            return self.kv
        dt = self.dtype or jnp.float32
        self.kv = [
            (jnp.zeros((self.num_pages, self.page_size, hd), dt),
             jnp.zeros((self.num_pages, self.page_size, hd), dt))
            for _ in range(self.num_layers)]
        return self.kv

    def bytes_per_token(self):
        """Device bytes one token's K+V occupies across all layers —
        the capacity math of docs/serving.md#quantized-kv: int8 pages
        cost heads*head_dim*1 + heads*4 (scale) per K and per V, dense
        pages heads*head_dim*itemsize."""
        hd = self.num_heads * self.head_dim
        if self.quantized:
            per = hd * 1 + self.num_heads * 4
        else:
            item = _np_dtype(self.dtype).itemsize if self.dtype else 4
            per = hd * item
        return 2 * per * self.num_layers

    def pool_bytes(self):
        """Total device bytes of the materialized (or to-be-
        materialized) pool arrays."""
        return self.num_pages * self.page_size * self.bytes_per_token()

    def drop_arrays(self):
        """Release the device buffers (engine shutdown)."""
        self.kv = None

    # -- allocator -----------------------------------------------------------
    def pages_for(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.page_size))

    @property
    def pages_in_use(self):
        return self.num_pages - len(self._free)

    @property
    def free_pages(self):
        return len(self._free)

    def utilization(self):
        return self.pages_in_use / self.num_pages

    def capacity_tokens(self, seq_id):
        """Tokens the sequence can hold without another allocation."""
        return len(self._seq_pages.get(seq_id, ())) * self.page_size

    def page_table(self, seq_id):
        return list(self._seq_pages.get(seq_id, ()))

    def owned_sequences(self):
        return list(self._seq_pages)

    def _take_page(self, seq_id):
        if not self._free:
            raise PoolExhausted(
                f"KV pool exhausted: {self.num_pages} pages of "
                f"{self.page_size} tokens all in use")
        page = self._free.pop()
        assert page not in self._owner, f"page {page} double-mapped"
        self._owner[page] = seq_id
        self._seq_pages.setdefault(seq_id, []).append(page)
        self.alloc_total += 1
        self.high_water = max(self.high_water, self.pages_in_use)
        return page

    def ensure_capacity(self, seq_id, n_tokens):
        """Grow seq_id's page list to hold n_tokens. Raises
        PoolExhausted (after rolling back nothing — partial growth is
        kept, the caller preempts and retries)."""
        need = self.pages_for(n_tokens)
        with self._lock:
            while len(self._seq_pages.get(seq_id, ())) < need:
                self._take_page(seq_id)
        return self._seq_pages[seq_id]

    def release(self, seq_id):
        """Return every page of seq_id to the free list."""
        with self._lock:
            pages = self._seq_pages.pop(seq_id, [])
            for page in pages:
                owner = self._owner.pop(page, None)
                assert owner == seq_id, \
                    f"page {page} owned by {owner}, freed by {seq_id}"
                self._free.append(page)
                self.free_total += 1
        return len(pages)

    def reset(self):
        with self._lock:
            self._free = list(range(self.num_pages - 1, -1, -1))
            self._owner.clear()
            self._seq_pages.clear()

    def census(self):
        """{seq_id: pages held} — who is sitting on the pool right now
        (the serve_report watchdog artifact embeds this so a stalled
        request's report names the page hogs)."""
        with self._lock:
            return {seq: len(pages)
                    for seq, pages in self._seq_pages.items()}

    def stats(self):
        return {
            'num_pages': self.num_pages,
            'page_size': self.page_size,
            'kv_dtype': ('int8' if self.quantized
                         else str(_np_dtype(self.dtype))
                         if self.dtype is not None else 'float32'),
            'bytes_per_token': self.bytes_per_token(),
            'pool_bytes': self.pool_bytes(),
            'pages_in_use': self.pages_in_use,
            'free_pages': self.free_pages,
            'utilization': self.utilization(),
            'high_water': self.high_water,
            'alloc_total': self.alloc_total,
            'free_total': self.free_total,
            'sequences': len(self._seq_pages),
        }
