"""Block-paged KV-cache pool (vLLM-style paging, TPU-shaped).

One fixed device tensor pair per decoder layer — `[num_pages,
page_size, local_heads * head_dim]` — shared by every in-flight
request. Sequences own pages through per-sequence page tables; a
host-side free-list allocator hands pages out and takes them back, so
KV memory is O(pages actually in use) instead of the dense cache's
O(batch * max_seq_len). The ragged paged-attention kernel gathers a
row's pages straight from this layout (`ops/pallas/paged_attention.py`
module docstring has the exact shapes).

Quantized pages (`kv_dtype='int8'`, ISSUE 7): each layer's entry
becomes a 4-tuple `(k_pages int8, v_pages int8, k_scales fp32,
v_scales fp32)` with scales of shape `[num_pages, page_size,
local_heads]` — one abs-max scale per (token slot, head), computed
when the token's K/V row is scattered in (`write_kv_pages_quantized`)
so already-written slots never rescale. Attention dequantizes inside
the kernel (or the dense fallback), so the math stays fp32 while the
pool holds ~4x (vs fp32) / ~2x (vs bf16) more tokens per byte; the
exact per-token byte math is `bytes_per_token()` below and
docs/serving.md#quantized-kv.

Copy-on-write prefix caching (ISSUE 9, `prefix_cache=True`): physical
pages are REFCOUNTED and a hash-chained prefix index maps token blocks
(granularity = page_size tokens) to the physical page that already
holds their K/V, so requests whose prompts share a prefix map their
page tables onto the same pages and skip the prefill compute for them.
The index key is `(parent_page, tuple(block_tokens))` — a radix chain
keyed by the previous block's *index* page, so a key identifies the
entire token prefix exactly (no hash-collision risk, and a block's K/V
is a pure function of the whole prefix, so dedup across requests is
sound). Only FULL pages are ever shared; a request diverging from a
cached prefix mid-page simply recomputes from the last shared page
boundary into a private page — that recompute IS the fork-on-write
(shared pages are append-only-immutable and never written: a request
always has >= 1 privately-prefilled token, so every page it scatters
into is private). Released pages whose content is still indexed park
in an LRU "cached" set: allocatable like free pages (eviction drops
the index subtree under them so a recycled page id can never satisfy a
stale chain), but a later matching prompt — including a preempted
request resuming — resurrects them for free. Int8 pools share scale
buffers automatically: scales are addressed by the same page id.

Host-RAM tier (ISSUE 20, `attach_host_tier`): under pool pressure,
cached (ref-0 parked) subtrees SPILL to a pinned host buffer pool
(`host_tier.HostTier`) instead of evicting — each spilled page's index
entry re-keys onto a negative HOST marker (`marker = -2 - host_slot`;
device pages are >= 0 and the chain root sentinel is -1, so markers
never collide), its children re-parent onto the marker, and the device
page unpins into the free list when the background transfer lands.
A matching prompt — or a preempted request resuming — walks the same
radix chain, finds the markers, and RESURRECTS the pages by host→device
prefetch instead of re-prefilling them; spill-in-flight device pages
sit in `_spilling`, outside free AND cached, so `try_reserve` and
`_take_page` see them as unavailable until landed.

The allocator is deliberately host-side and dumb-simple: serving
decisions (admit / grow / preempt) happen between jitted steps, where
Python cost is amortized over a whole batch step. Invariants it
enforces (tested in tests/test_serving.py):

  * a page's refcount equals the number of sequences mapping it
    (exactly one owner unless prefix sharing maps it again);
  * free + cached + mapped partitions the pool at all times;
  * release drops every page of a sequence exactly once — a page
    returns to the free/cached set only when its LAST mapper lets go.
"""
import collections
import hashlib
import math
import struct
import threading

import numpy as _np


def chain_hash(parent_hash, block_tokens):
    """64-bit hash of one radix-chain link: the parent chain hash plus
    this block's tokens. Stable across processes (blake2b, fixed
    little-endian packing) — the disaggregated router hashes a prompt's
    block chain with exactly this function and compares against the
    digests each replica publishes from its own prefix index
    (cluster/router.py prefix-affinity placement)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(struct.pack('<Q',
                         int(parent_hash) & 0xFFFFFFFFFFFFFFFF))
    h.update(_np.asarray(list(block_tokens), '<i4').tobytes())
    return int.from_bytes(h.digest(), 'little')


def chain_hashes(tokens, page_size, limit=None):
    """Chain hashes of every FULL page_size-token block of `tokens`
    (capped at `limit` tokens), in chain order — h[i] identifies the
    whole prefix up to block i, matching the pool's radix-index
    identity (kv_pool docstring)."""
    n = len(tokens) if limit is None else min(len(tokens),
                                              max(int(limit), 0))
    out, h = [], -1
    for i in range(n // int(page_size)):
        h = chain_hash(h, tokens[i * page_size:(i + 1) * page_size])
        out.append(h)
    return out


def _np_dtype(dt):
    """np.dtype of a string / numpy / jnp dtype spec without importing
    jax for the common cases (pure-allocator tests stay jax-free)."""
    try:
        return _np.dtype(dt)
    except TypeError:
        import jax.numpy as jnp
        return _np.dtype(jnp.dtype(dt))


class PoolExhausted(RuntimeError):
    """No free pages — the scheduler's cue to stop admitting or to
    preempt a victim (engine.py)."""


class KVPagePool:
    """Free-list page allocator + the paged device arrays.

    Device arrays are created lazily (`materialize()`) so pure
    allocator tests never touch jax; the engine materializes once at
    build. `kv[l]` is the (k_pages, v_pages) pair of layer l.
    """

    def __init__(self, num_pages, page_size, num_layers=0, num_heads=0,
                 head_dim=0, dtype=None, prefix_cache=False):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.prefix_cache = bool(prefix_cache)
        self.kv = None                      # [(k_pages, v_pages)] per layer
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._ref = {}                      # page id -> mapper count
        self._owners = {}                   # page id -> set of seq ids
        self._seq_pages = {}                # seq id -> [page ids]
        # prefix index: (parent index page | -1, block token tuple) ->
        # physical page; _cached is the LRU set of ref-0-but-indexed
        # pages (allocatable, resurrectable)
        self._index = {}
        self._page_key = {}                 # page id -> its index key
        self._children = {}                 # page id -> child page ids
        self._cached = collections.OrderedDict()
        self._registered_upto = {}          # seq id -> tokens indexed
        # weighted eviction (ISSUE 15): indexed pages remember the
        # tenant whose request first registered them; when the
        # degradation ladder reaches stage 3 the engine installs
        # per-tenant weights and cached-subtree eviction picks the
        # LIGHTEST tenant's LRU root instead of the global LRU —
        # a heavy tenant under overload loses its own cache first
        self._page_tenant = {}              # page id -> tenant id|None
        self._evict_weights = None          # tenant id -> weight|None
        self._digest_cache = None           # (limit, hashes) memo —
                                            # invalidated on any index
                                            # mutation; status() polls
                                            # this several times a
                                            # second per replica
        self._lock = threading.Lock()
        # host-RAM tier (ISSUE 20): markers (<= -2) live in _index /
        # _page_key / _children like device pages; _spilling pins
        # device pages whose spill is in flight (outside free AND
        # cached — no allocation path can hand them out)
        self.host_tier = None
        self._spilling = set()
        self.host_resurrect_pages = 0
        self.host_resurrect_tokens = 0
        self._pending_resurrect = None      # engine pops for trace/ledger
        self.alloc_total = 0
        self.free_total = 0
        self.high_water = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self.prefix_evictions = 0

    def attach_host_tier(self, tier):
        """Install the host-RAM tier (host_tier.HostTier). Must happen
        before any spill; the pool never constructs one itself so pure
        allocator tests stay tier-free."""
        self.host_tier = tier
        return tier

    # -- device arrays -------------------------------------------------------
    @property
    def quantized(self):
        """True when pages store int8 + per-(slot, head) fp32 scales."""
        if self.dtype is None:
            return False
        return _np_dtype(self.dtype) == _np.int8

    def materialize(self, sharding=None):
        """Create the device arrays. `sharding` (a NamedSharding whose
        spec splits the trailing heads*hd axis, e.g. P(None, None,
        'mp')) places the pool sharded over a replica-local mesh for
        the mp-sharded serving route — each mp shard then holds its
        local heads' pages, exactly the layout forward_paged's
        column-sharded qkv writes (docs/serving.md#mp-sharding)."""
        if self.kv is not None:
            return self.kv
        import jax.numpy as jnp

        def _z(shape, dt):
            arr = jnp.zeros(shape, dt)
            if sharding is not None:
                import jax
                arr = jax.device_put(arr, sharding)
            return arr

        hd = self.num_heads * self.head_dim
        if self.quantized:
            shape = (self.num_pages, self.page_size, hd)
            sshape = (self.num_pages, self.page_size, self.num_heads)
            self.kv = [
                (_z(shape, jnp.int8), _z(shape, jnp.int8),
                 _z(sshape, jnp.float32), _z(sshape, jnp.float32))
                for _ in range(self.num_layers)]
            return self.kv
        dt = self.dtype or jnp.float32
        self.kv = [
            (_z((self.num_pages, self.page_size, hd), dt),
             _z((self.num_pages, self.page_size, hd), dt))
            for _ in range(self.num_layers)]
        return self.kv

    def bytes_per_token(self):
        """Device bytes one token's K+V occupies across all layers —
        the capacity math of docs/serving.md#quantized-kv: int8 pages
        cost heads*head_dim*1 + heads*4 (scale) per K and per V, dense
        pages heads*head_dim*itemsize."""
        hd = self.num_heads * self.head_dim
        if self.quantized:
            per = hd * 1 + self.num_heads * 4
        else:
            item = _np_dtype(self.dtype).itemsize if self.dtype else 4
            per = hd * item
        return 2 * per * self.num_layers

    def pool_bytes(self):
        """Total device bytes of the materialized (or to-be-
        materialized) pool arrays."""
        return self.num_pages * self.page_size * self.bytes_per_token()

    def drop_arrays(self):
        """Release the device buffers (engine shutdown)."""
        self.kv = None

    # -- allocator -----------------------------------------------------------
    def pages_for(self, n_tokens):
        return max(1, math.ceil(n_tokens / self.page_size))

    @property
    def pages_in_use(self):
        """Pages mapped by at least one sequence. Cached (indexed but
        unmapped) pages are reclaimable and count as free."""
        return self.num_pages - len(self._free) - len(self._cached)

    @property
    def free_pages(self):
        """Allocatable pages: truly free + cached-evictable."""
        return len(self._free) + len(self._cached)

    @property
    def cached_pages(self):
        return len(self._cached)

    @property
    def shared_pages(self):
        """Physical pages currently mapped by more than one sequence."""
        return sum(1 for r in self._ref.values() if r > 1)

    def utilization(self):
        return self.pages_in_use / self.num_pages

    def capacity_tokens(self, seq_id):
        """Tokens the sequence can hold without another allocation."""
        return len(self._seq_pages.get(seq_id, ())) * self.page_size

    def reclaimable_pages(self, seq_id):
        """Pages release(seq_id) would actually free right now (the
        seq is their only mapper) — the admission sweep's preemption-
        feasibility estimate: preempting a victim whose pages are all
        shared reclaims nothing, so the sweep must not destroy its
        work for a budget that still won't cover the admit."""
        with self._lock:
            return sum(1 for p in self._seq_pages.get(seq_id, ())
                       if self._ref.get(p) == 1)

    def page_table(self, seq_id):
        return list(self._seq_pages.get(seq_id, ()))

    def owned_sequences(self):
        return list(self._seq_pages)

    def _evict_subtree(self, page):
        """Drop `page` and every index descendant from the prefix
        index, returning the cached (ref-0) ones to the free list.
        Dropping descendants with the parent is a correctness
        requirement, not just hygiene: the freed page id will be
        recycled, and a surviving child keyed on it could satisfy a
        stale chain. A descendant a live sequence still maps (possible
        when registration dedup chained it through a canonical page
        its owner never mapped) is only DE-indexed — it lives on as a
        plain private page and frees normally at release. Iterative:
        chains grow one node per page of a sequence, which at small
        page sizes is deeper than Python's recursion limit."""
        self._digest_cache = None
        stack = [page]
        while stack:
            p = stack.pop()
            stack.extend(self._children.pop(p, ()))
            key = self._page_key.pop(p)
            del self._index[key]
            parent = key[0]
            if parent != -1 and parent in self._children:
                self._children[parent].discard(p)
            self._page_tenant.pop(p, None)
            if p <= -2:
                # host-tier node: the index entry IS the page — drop it
                # and hand the host slot back (device side owes nothing)
                if self.host_tier is not None:
                    self.host_tier.free_slot(-2 - p)
                self.prefix_evictions += 1
            elif p in self._cached:
                del self._cached[p]
                self._free.append(p)
                self.prefix_evictions += 1

    def set_eviction_weights(self, weights):
        """Install (or clear, with None) per-tenant eviction weights.
        While set, cached-subtree eviction under allocation pressure
        picks the root whose owning tenant has the LOWEST weight
        (LRU order within a weight class; unowned pages weigh 1.0)
        instead of pure LRU — the degradation ladder's stage-3 lever
        (docs/serving.md#multi-tenant)."""
        self._evict_weights = (None if weights is None
                               else {str(k): float(v)
                                     for k, v in weights.items()})

    def _pick_eviction_root(self):
        """The cached page eviction starts from: global LRU normally;
        under weighted eviction, the LRU cached page of the lightest-
        weight owning tenant."""
        if self._evict_weights is None:
            return next(iter(self._cached))
        w = self._evict_weights
        return min(self._cached,
                   key=lambda p: w.get(self._page_tenant.get(p), 1.0))

    # -- host-RAM tier (ISSUE 20) --------------------------------------------
    def _rekey_node(self, old, new):
        """Move a radix node's identity from ref `old` to ref `new`:
        its index entry, its children's keys (they chain through the
        parent REF), the parent's child set, and the tenant tag. The
        spill (page -> marker) and resurrect (marker -> page)
        directions are the same bookkeeping."""
        key = self._page_key.pop(old)
        del self._index[key]
        self._index[key] = new
        self._page_key[new] = key
        parent = key[0]
        if parent != -1 and parent in self._children:
            self._children[parent].discard(old)
            self._children[parent].add(new)
        kids = self._children.pop(old, None)
        if kids:
            self._children[new] = kids
            for c in list(kids):
                ckey = self._page_key.pop(c)
                nkey = (new, ckey[1])
                del self._index[ckey]
                self._index[nkey] = c
                self._page_key[c] = nkey
        tn = self._page_tenant.pop(old, None)
        if tn is not None:
            self._page_tenant[new] = tn
        self._digest_cache = None

    def _spill_landed_locked(self, pages):
        for p in pages:
            if p in self._spilling:
                self._spilling.discard(p)
                self._free.append(p)

    def _spill_landed(self, pages):
        with self._lock:
            self._spill_landed_locked(pages)

    def _spill_prepare(self, root):
        """Re-key `root`'s cached (ref-0) subtree pages onto HOST
        markers and pin them in `_spilling` — the index mutation half
        of a spill, lock held by caller. A matching prompt still
        chain-walks to the markers; live descendants (mapped by a
        sequence) stay device-resident — only their chain link
        re-parents. Slot allocation is all-or-nothing per subtree (a
        half-spilled subtree would split its chain); a full tier
        prepares nothing and the caller falls back to plain eviction.
        Returns (device_pages, host_slots) or None. The TRANSFER is
        the caller's job: synchronous inline, or queued to the
        background thread OUTSIDE the pool lock (the bounded window
        semaphore must never be waited on while holding the lock the
        landed-callback needs)."""
        tier = self.host_tier
        if tier is None or self.kv is None:
            return None
        cached = []
        stack = [root]
        while stack:                # parents visit before children, so
            p = stack.pop()         # a child re-keys under its parent's
            stack.extend(self._children.get(p, ()))     # marker
            if p in self._cached:
                cached.append(p)
        if not cached:
            return None
        slots = tier.alloc_slots(len(cached))
        if slots is None:
            return None
        for p, slot in zip(cached, slots):
            self._rekey_node(p, -2 - slot)
            del self._cached[p]
            self._spilling.add(p)
        return cached, slots

    def _spill_subtree(self, root, sync=True):
        """Synchronous spill of `root`'s cached subtree (the
        `_take_page` exhaustion path — the page is needed NOW). Lock
        held by caller. Returns the device pages spilled."""
        assert sync, "async spills go through spill_lru"
        prep = self._spill_prepare(root)
        if prep is None:
            return []
        pages, slots = prep
        self.host_tier.spill_sync(self.kv, pages, slots)
        self._spill_landed_locked(pages)
        return pages

    def spill_lru(self, max_pages=None, sync=False):
        """Spill LRU-parked cached subtrees (preempted requests'
        released pages land there too) until `max_pages` device pages
        are spilling (None = the whole parked set). The engine's
        proactive spiller calls this when utilization crosses the
        spill watermark, keeping the free list stocked so allocation
        never has to spill synchronously. Returns pages spilled.

        Async jobs are submitted AFTER the lock is released: the
        tier's bounded in-flight window can block the producer, and
        the landed callback that unblocks it needs this lock — queueing
        under the lock would deadlock the pair. The pinned pages'
        contents are immutable until landed and `self.kv` only swaps
        on the engine thread (the thread running this), so staging
        outside the lock reads exactly the rows that were pinned."""
        if self.host_tier is None:
            return 0
        n = 0
        jobs = []
        with self._lock:
            while self._cached and (max_pages is None or n < max_pages):
                prep = self._spill_prepare(self._pick_eviction_root())
                if prep is None:
                    break
                pages, slots = prep
                if sync:
                    self.host_tier.spill_sync(self.kv, pages, slots)
                    self._spill_landed_locked(pages)
                else:
                    jobs.append((pages, slots))
                n += len(pages)
        for pages, slots in jobs:
            self.host_tier.submit_spill(
                self.kv, pages, slots,
                on_landed=lambda pages=list(pages):
                    self._spill_landed(pages))
        return n

    def host_resident_pages(self):
        """Pages currently host-resident (markers in the index)."""
        with self._lock:
            return sum(1 for p in self._page_key if p <= -2)

    def pop_resurrect_stats(self):
        """Pop the pending resurrect accounting (pages/tokens fetched
        since the last pop) — the engine turns it into a `resurrect`
        trace event and ledger page_stream attribution."""
        with self._lock:
            r, self._pending_resurrect = self._pending_resurrect, None
        return r

    def _resurrect_locked(self, markers, seq_id=None):
        """Fetch host-resident `markers` back into device pages: parked
        (cached, ref-0) pages when seq_id is None (the router's warm
        hint), mapped into seq_id's table otherwise. Lock held by
        caller; allocation uses only the free list on the warm path
        (a hint never evicts). Returns the device pages, aligned with
        `markers` (shorter when the pool ran out mid-chain)."""
        tier = self.host_tier
        devs, slots = [], []
        for m in markers:
            if m not in self._page_key:
                break               # destroyed under us by an eviction
            if seq_id is not None:
                try:
                    page = self._take_page(seq_id)
                except PoolExhausted:
                    break
            else:
                if not self._free:
                    break
                page = self._free.pop()
            devs.append(page)
            slots.append(-2 - m)
            self._rekey_node(m, page)
            if seq_id is None:
                self._cached[page] = None       # parked, LRU newest
        if devs:
            self.kv = tier.fetch(self.kv, slots, devs)
            for s in slots:
                tier.free_slot(s)
            self.host_resurrect_pages += len(devs)
            self.host_resurrect_tokens += len(devs) * self.page_size
            pend = self._pending_resurrect or {'pages': 0, 'tokens': 0}
            pend['pages'] += len(devs)
            pend['tokens'] += len(devs) * self.page_size
            self._pending_resurrect = pend
        return devs

    def warm_prefix(self, tokens, limit=None):
        """Advisory host→device prefetch (the router's prefix-affinity
        hint): resurrect the host-resident pages of the longest
        indexed chain for `tokens` into PARKED (cached, ref-0) device
        pages, so the request that follows prefix-hits device pages
        with zero transfer on its own critical path. Uses only truly
        free pages — a hint never evicts or preempts — and stops at
        the first unavailable page. Returns pages warmed."""
        if self.host_tier is None or not self.prefix_cache:
            return 0
        with self._lock:
            refs = self._match_pages(tokens, limit)
            markers = [m for m in refs if m <= -2]
            return len(self._resurrect_locked(markers, seq_id=None))

    def _take_page(self, seq_id):
        if not self._free and self._cached:
            # host tier first (ISSUE 20): spill the LRU cached subtree
            # synchronously — the page is needed NOW and the proactive
            # spiller didn't keep up — so its prefix survives as
            # host-resident markers instead of evaporating
            if self.host_tier is not None and self.kv is not None:
                self._spill_subtree(self._pick_eviction_root(),
                                    sync=True)
            if not self._free and self._cached:
                # evict the least-recently-used cached prefix subtree
                # (weight-ordered when eviction weights are installed)
                self._evict_subtree(self._pick_eviction_root())
        if not self._free:
            raise PoolExhausted(
                f"KV pool exhausted: {self.num_pages} pages of "
                f"{self.page_size} tokens all in use")
        page = self._free.pop()
        assert page not in self._ref, f"page {page} double-mapped"
        self._ref[page] = 1
        self._owners[page] = {seq_id}
        self._seq_pages.setdefault(seq_id, []).append(page)
        self.alloc_total += 1
        self.high_water = max(self.high_water, self.pages_in_use)
        return page

    def _map_existing(self, page, seq_id):
        """Map an already-resident page into seq_id's table: incref a
        live page, or resurrect a cached one (ref 0 -> 1)."""
        if page in self._cached:
            del self._cached[page]
            self._ref[page] = 1
            self._owners[page] = {seq_id}
        else:
            self._ref[page] += 1
            self._owners[page].add(seq_id)
        self._seq_pages.setdefault(seq_id, []).append(page)
        self.high_water = max(self.high_water, self.pages_in_use)

    def ensure_capacity(self, seq_id, n_tokens):
        """Grow seq_id's page list to hold n_tokens. Raises
        PoolExhausted (after rolling back nothing — partial growth is
        kept, the caller preempts and retries)."""
        need = self.pages_for(n_tokens)
        with self._lock:
            while len(self._seq_pages.get(seq_id, ())) < need:
                self._take_page(seq_id)
        return self._seq_pages[seq_id]

    def try_reserve(self, seq_id, n_tokens):
        """Grow seq_id's page list to hold n_tokens, or change NOTHING
        — the fused decode window's all-or-nothing reservation (ISSUE
        19). Unlike ensure_capacity (partial growth kept because its
        caller preempts and retries), a failed reservation rolls its
        own fresh pages straight back: the engine falls back to the
        [B, 1] step for this dispatch instead of preempting, so the
        pool must come out untouched. Returns True when the pages are
        held. Fresh pages are private and unindexed by construction,
        so the rollback mirrors trim's bookkeeping."""
        need = self.pages_for(n_tokens)
        with self._lock:
            grown = 0
            try:
                while len(self._seq_pages.get(seq_id, ())) < need:
                    self._take_page(seq_id)
                    grown += 1
            except PoolExhausted:
                pages = self._seq_pages.get(seq_id, [])
                for _ in range(grown):
                    page = pages.pop()
                    del self._ref[page]
                    del self._owners[page]
                    self._free.append(page)
                    self.free_total += 1
                return False
        return True

    def release(self, seq_id):
        """Drop seq_id's mapping of every page it holds, exactly once
        per page. A page whose refcount reaches zero becomes
        reclaimable: indexed pages park in the cached (LRU,
        resurrectable) set, unindexed ones return to the free list.
        Pages a sibling still references stay mapped — preemption can
        never evict a live sharer's prefix. Returns the number of
        pages made reclaimable."""
        with self._lock:
            pages = self._seq_pages.pop(seq_id, [])
            self._registered_upto.pop(seq_id, None)
            reclaimed = 0
            for page in pages:
                owners = self._owners.get(page)
                assert owners is not None and seq_id in owners, \
                    f"page {page} owned by {owners}, freed by {seq_id}"
                owners.discard(seq_id)
                self._ref[page] -= 1
                if self._ref[page] > 0:
                    continue
                del self._ref[page]
                del self._owners[page]
                if page in self._page_key:
                    self._cached[page] = None       # LRU newest
                else:
                    self._free.append(page)
                reclaimed += 1
                self.free_total += 1
        return reclaimed

    def trim(self, seq_id, n_tokens):
        """Give back trailing pages beyond what n_tokens needs — the
        speculative-decode rollback: the verify step grows the table
        for k drafts, rejected ones hand their pages straight back.
        Only private unindexed tail pages are trimmed (shared or
        indexed pages stay; their slots are overwritten in place by
        later writes). Returns the number of pages freed."""
        keep = self.pages_for(n_tokens)
        with self._lock:
            pages = self._seq_pages.get(seq_id, [])
            freed = 0
            while len(pages) > keep:
                page = pages[-1]
                if self._ref.get(page) != 1 or page in self._page_key:
                    break
                pages.pop()
                del self._ref[page]
                del self._owners[page]
                self._free.append(page)
                freed += 1
                self.free_total += 1
        return freed

    def reset(self):
        with self._lock:
            if self.host_tier is not None:
                for p in self._page_key:
                    if p <= -2:
                        self.host_tier.free_slot(-2 - p)
            self._spilling.clear()
            self._pending_resurrect = None
            self._free = list(range(self.num_pages - 1, -1, -1))
            self._ref.clear()
            self._owners.clear()
            self._seq_pages.clear()
            self._index.clear()
            self._page_key.clear()
            self._children.clear()
            self._cached.clear()
            self._registered_upto.clear()
            self._page_tenant.clear()
            self._digest_cache = None

    # -- prefix index --------------------------------------------------------
    def _match_pages(self, tokens, limit=None):
        """Walk the index chain over full token blocks; returns the
        matched physical pages (longest indexed prefix, in order)."""
        ps = self.page_size
        n = len(tokens) if limit is None else min(len(tokens),
                                                  max(int(limit), 0))
        pages, parent = [], -1
        for i in range(n // ps):
            page = self._index.get(
                (parent, tuple(tokens[i * ps:(i + 1) * ps])))
            if page is None:
                break
            pages.append(page)
            parent = page
        return pages

    def peek_prefix(self, tokens, limit=None):
        """Non-mutating admission probe: (cached_tokens, live_pages,
        resurrect_pages, host_pages). Live pages are mapped by a
        sibling and cost the page budget nothing; resurrect pages sit
        in the device cached set and cost one allocatable page each
        (they just skip the prefill compute); host pages (ISSUE 20)
        also cost one allocatable page each PLUS a host→device
        transfer — the engine budgets them as transfer cost, not
        compute."""
        if not self.prefix_cache:
            return 0, 0, 0, 0
        with self._lock:
            pages = self._match_pages(tokens, limit)
            live = sum(1 for p in pages if self._ref.get(p, 0) > 0)
            host = sum(1 for p in pages if p <= -2)
        return (len(pages) * self.page_size, live,
                len(pages) - live - host, host)

    def match_and_map(self, seq_id, tokens, limit=None):
        """Map the longest indexed prefix of `tokens` (full blocks,
        capped at `limit` tokens) into seq_id's page table, increffing
        live pages and resurrecting cached ones. Returns the number of
        prefix tokens now covered — the caller skips prefilling them.
        Counted as one hit (or miss) per lookup."""
        if not self.prefix_cache:
            return 0
        with self._lock:
            if self._seq_pages.get(seq_id):
                # the seq already allocated (e.g. a prior prefill
                # attempt grew partial pages before PoolExhausted and
                # the caller retried): shared pages must sit at the
                # FRONT of the table, so just prefill privately
                return 0
            pages = self._match_pages(tokens, limit)
            if self.host_tier is not None and any(p <= -2
                                                 for p in pages):
                mapped = self._match_and_map_tiered(seq_id, tokens,
                                                    limit)
            else:
                for page in pages:
                    self._map_existing(page, seq_id)
                mapped = len(pages)
            if not mapped:
                self.prefix_misses += 1
                return 0
            cached = mapped * self.page_size
            self.prefix_hits += 1
            self.prefix_hit_tokens += cached
            self._registered_upto[seq_id] = cached
        return cached

    def _match_and_map_tiered(self, seq_id, tokens, limit=None):
        """match_and_map's slow path when the matched chain crosses
        host-resident markers: walk the index LIVE block by block
        (resurrection re-keys nodes and allocation pressure may evict
        or spill under us, so a pre-computed match would go stale),
        mapping device pages and fetching each contiguous marker run
        back in one chunked transfer. Lock held by caller. Returns
        full blocks mapped."""
        ps = self.page_size
        n = len(tokens) if limit is None else min(len(tokens),
                                                  max(int(limit), 0))
        blocks = n // ps

        def _block(j):
            return tuple(tokens[j * ps:(j + 1) * ps])

        parent, mapped, i = -1, 0, 0
        while i < blocks:
            ref = self._index.get((parent, _block(i)))
            if ref is None:
                break
            if ref <= -2:
                run, cur, j = [ref], ref, i + 1
                while j < blocks:
                    nxt = self._index.get((cur, _block(j)))
                    if nxt is None or nxt > -2:
                        break
                    run.append(nxt)
                    cur = nxt
                    j += 1
                devs = self._resurrect_locked(run, seq_id)
                mapped += len(devs)
                i += len(devs)
                if len(devs) < len(run):
                    return mapped       # pool ran out mid-chain: the
                                        # prefix covered so far stands
                parent = devs[-1] if devs else parent
            else:
                self._map_existing(ref, seq_id)
                mapped += 1
                i += 1
                parent = ref
        return mapped

    def register_prefix(self, seq_id, tokens, written, owner=None):
        """Index seq_id's newly completed full pages (first `written`
        tokens of `tokens` have K/V resident) so later requests can
        share them. A block already indexed elsewhere is NOT
        re-registered — the chain advances through the canonical page
        (dedup), and this sequence's private twin stays unindexed.
        The walk starts from the chain root every call (cheap: a few
        dict hits per resident block) so a chain broken by eviction
        self-heals from this sequence's own pages instead of chaining
        onto a stale — possibly recycled — parent id.

        `owner` (a tenant id) tags newly indexed pages for weighted
        eviction — the tenant whose request FIRST registered a page
        owns it for eviction purposes (shared pages keep their
        original owner; re-registration never re-tags)."""
        if not self.prefix_cache:
            return
        ps = self.page_size
        with self._lock:
            blocks = min(int(written), len(tokens)) // ps
            if blocks * ps <= self._registered_upto.get(seq_id, 0):
                return
            seq_pages = self._seq_pages.get(seq_id, [])
            parent = -1
            for i in range(min(blocks, len(seq_pages))):
                key = (parent, tuple(tokens[i * ps:(i + 1) * ps]))
                page = self._index.get(key)
                if page is None:
                    page = seq_pages[i]
                    if page in self._page_key:      # already chained
                        break                       # under another key
                    self._index[key] = page
                    self._page_key[page] = key
                    if owner is not None:
                        self._page_tenant[page] = str(owner)
                    self._digest_cache = None
                    if parent != -1:
                        self._children.setdefault(parent,
                                                  set()).add(page)
                parent = page
            self._registered_upto[seq_id] = blocks * ps

    def prefix_chain_hashes(self, limit=4096):
        """Chain hashes (chain_hash above) of every chain indexed in
        the prefix index, capped at `limit` entries — the affinity
        digest a serving replica publishes so the cluster router can
        route a prompt to the replica that already holds its prefix
        pages. A hash is present exactly when the corresponding token
        chain would prefix-hit here (match_and_map walks the same
        radix links). Memoized: the replica status loop reads this
        several times a second, and re-hashing thousands of chains
        under the pool lock would stall the allocator — the memo
        invalidates whenever the index gains or loses a chain."""
        if not self.prefix_cache:
            return []
        out = []
        with self._lock:
            memo = self._digest_cache
            if memo is not None and memo[0] == limit:
                return list(memo[1])
            roots = [(key, page) for key, page in self._index.items()
                     if key[0] == -1]
            stack = [(-1, key, page) for key, page in roots]
            while stack and len(out) < int(limit):
                parent_hash, key, page = stack.pop()
                h = chain_hash(parent_hash, key[1])
                out.append(h)
                for child in self._children.get(page, ()):
                    ckey = self._page_key.get(child)
                    if ckey is not None:
                        stack.append((h, ckey, child))
            self._digest_cache = (limit, list(out))
        return out

    def census(self):
        """{seq_id: pages held} — who is sitting on the pool right now
        (the serve_report watchdog artifact embeds this so a stalled
        request's report names the page hogs)."""
        with self._lock:
            return {seq: len(pages)
                    for seq, pages in self._seq_pages.items()}

    def stats(self):
        s = {
            'num_pages': self.num_pages,
            'page_size': self.page_size,
            'kv_dtype': ('int8' if self.quantized
                         else str(_np_dtype(self.dtype))
                         if self.dtype is not None else 'float32'),
            'bytes_per_token': self.bytes_per_token(),
            'pool_bytes': self.pool_bytes(),
            'pages_in_use': self.pages_in_use,
            'free_pages': self.free_pages,
            'utilization': self.utilization(),
            'high_water': self.high_water,
            'alloc_total': self.alloc_total,
            'free_total': self.free_total,
            'sequences': len(self._seq_pages),
            'prefix_cache': self.prefix_cache,
            'cached_pages': self.cached_pages,
            'shared_pages': self.shared_pages,
            'prefix_hits_total': self.prefix_hits,
            'prefix_misses_total': self.prefix_misses,
            'prefix_hit_tokens_total': self.prefix_hit_tokens,
            'prefix_evictions_total': self.prefix_evictions,
            'weighted_eviction': self._evict_weights is not None,
        }
        if self.host_tier is not None:
            s.update(self.host_tier.stats())
            s['tier_resurrected_pages_total'] = self.host_resurrect_pages
            s['tier_resurrected_tokens_total'] = \
                self.host_resurrect_tokens
            s['tier_spill_inflight_pages'] = len(self._spilling)
        return s
